"""ctypes bindings for the C++ native runtime (libdeflate BGZF codec).

The shared library is built lazily from the bundled source on first use
(g++ -O3 against the system libdeflate) and cached next to this module;
every consumer degrades gracefully to the pure-Python/zlib path when the
toolchain or libdeflate is unavailable (set FGUMI_TPU_NO_NATIVE=1 to force
the fallback). Mirrors the reference's native layering (SURVEY.md §2 intro:
C++ equivalents for the L1-L4 hot paths).
"""

import ctypes
import logging
import os
import subprocess
import threading

log = logging.getLogger("fgumi_tpu")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "libfgumi_native.so")
_SRC_PATH = os.path.join(_HERE, "fgumi_native.cc")

_lock = threading.Lock()
_lib = None
_lib_failed = False
# must equal fgumi_abi_version() in fgumi_native.cc (stale-.so guard)
_ABI_VERSION = 14


def _build() -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-o", _SO_PATH,
           _SRC_PATH, "-ldeflate"]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.debug("native build failed to launch: %s", e)
        return False
    if proc.returncode != 0:
        log.debug("native build failed:\n%s", proc.stderr)
        return False
    return True


def _declare(lib):
    """ctypes restype/argtypes for every export (one copy, used by both
    the cached-build path and the FGUMI_TPU_NATIVE_SO override)."""
    p = ctypes.c_void_p
    lib.fgumi_duplex_rx_fast.restype = ctypes.c_long
    lib.fgumi_duplex_rx_fast.argtypes = [
        p, p, p, p, p, p, ctypes.c_long, p, ctypes.c_long, p, p, p, p]
    lib.fgumi_codec_combine.restype = None
    lib.fgumi_codec_combine.argtypes = [
        p, p, p, p, p, p, p, p, ctypes.c_long, ctypes.c_int, ctypes.c_ubyte,
        ctypes.c_ubyte, ctypes.c_int, p, p, p, p, p, p]
    lib.fgumi_bgzf_decompress.restype = ctypes.c_long
    lib.fgumi_bgzf_decompress.argtypes = [
        ctypes.c_void_p, ctypes.c_long, ctypes.c_void_p, ctypes.c_long,
        ctypes.POINTER(ctypes.c_long)]
    lib.fgumi_gzip_decompress.restype = ctypes.c_long
    lib.fgumi_gzip_decompress.argtypes = [
        ctypes.c_void_p, ctypes.c_long, ctypes.c_void_p, ctypes.c_long]
    lib.fgumi_umi_neighbor_pairs.restype = ctypes.c_long
    lib.fgumi_umi_neighbor_pairs.argtypes = [
        p, ctypes.c_long, p, ctypes.c_long, ctypes.c_long, ctypes.c_int,
        p, p, ctypes.c_long]
    lib.fgumi_umi_bktree_pairs.restype = ctypes.c_long
    lib.fgumi_umi_bktree_pairs.argtypes = [
        p, ctypes.c_long, p, ctypes.c_long, ctypes.c_long, ctypes.c_int,
        p, p, ctypes.c_long]
    lib.fgumi_adjacency_bfs.restype = None
    lib.fgumi_adjacency_bfs.argtypes = [p, p, p, ctypes.c_long, p]
    lib.fgumi_bgzf_compress_block.restype = ctypes.c_long
    lib.fgumi_bgzf_compress_block.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_int, ctypes.c_char_p,
        ctypes.c_long]
    lib.fgumi_zlib_compress.restype = ctypes.c_long
    lib.fgumi_zlib_compress.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_int, ctypes.c_char_p,
        ctypes.c_long]
    lib.fgumi_zlib_decompress.restype = ctypes.c_long
    lib.fgumi_zlib_decompress.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_char_p, ctypes.c_long]
    lib.fgumi_find_record_boundaries.restype = ctypes.c_long
    lib.fgumi_find_record_boundaries.argtypes = [
        ctypes.c_char_p, ctypes.c_long,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_long,
        ctypes.POINTER(ctypes.c_int64)]
    # batch record layer: all pointers passed as raw addresses (numpy
    # array .ctypes.data); see fgumi_tpu/native/batch.py wrappers.
    lib.fgumi_decode_fields.restype = None
    lib.fgumi_decode_fields.argtypes = [p, p, ctypes.c_long] + [p] * 12
    lib.fgumi_scan_tags.restype = None
    lib.fgumi_scan_tags.argtypes = [p, p, p, ctypes.c_long, p,
                                    ctypes.c_long, p, p, p]
    lib.fgumi_group_starts.restype = ctypes.c_long
    lib.fgumi_group_starts.argtypes = [p, p, p, ctypes.c_long, p]
    lib.fgumi_pack_reads.restype = None
    lib.fgumi_pack_reads.argtypes = [p, p, p, p, p, p, ctypes.c_long,
                                     ctypes.c_int, ctypes.c_long,
                                     ctypes.c_int, p, p, p]
    lib.fgumi_mate_clips.restype = None
    lib.fgumi_mate_clips.argtypes = [p] * 11 + [ctypes.c_long, p]
    lib.fgumi_overlap_correct_pairs.restype = None
    lib.fgumi_overlap_correct_pairs.argtypes = [
        p, p, p, ctypes.c_long, ctypes.c_int, ctypes.c_int, p]
    lib.fgumi_build_consensus_records.restype = ctypes.c_long
    lib.fgumi_build_consensus_records.argtypes = (
        [p] * 6 + [ctypes.c_long, p, ctypes.c_int, p, p, p, p, p,
                   ctypes.c_int, ctypes.c_int, p, ctypes.c_long, p])
    lib.fgumi_build_duplex_records.restype = ctypes.c_long
    lib.fgumi_build_duplex_records.argtypes = (
        [p] * 5 + [ctypes.c_long, p, ctypes.c_int, p, p]
        + [p] * 5 + [p] * 6 + [p, p, p, ctypes.c_int, ctypes.c_int,
                               p, ctypes.c_long, p])
    lib.fgumi_build_codec_records.restype = ctypes.c_long
    lib.fgumi_build_codec_records.argtypes = (
        [p] * 11 + [p, ctypes.c_long] + [p] * 6
        + [p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
           p, ctypes.c_long, p])
    lib.fgumi_segment_depth_errors.restype = None
    lib.fgumi_segment_depth_errors.argtypes = (
        [p, p, p, ctypes.c_long, ctypes.c_long, p, p])
    lib.fgumi_segment_depth_errors_ranges.restype = None
    lib.fgumi_segment_depth_errors_ranges.argtypes = (
        [p, p, p, p, ctypes.c_long, ctypes.c_long, p, p])
    lib.fgumi_consensus_segments.restype = ctypes.c_long
    lib.fgumi_consensus_segments.argtypes = (
        [p, p, p, ctypes.c_long, ctypes.c_long, p, p, ctypes.c_double,
         ctypes.c_int, ctypes.c_int] + [p] * 8 + [p, p, p, ctypes.c_long])
    lib.fgumi_consensus_classify.restype = ctypes.c_long
    lib.fgumi_consensus_classify.argtypes = (
        [p, p, p, ctypes.c_long, ctypes.c_long, p, ctypes.c_double,
         ctypes.c_int, ctypes.c_int] + [p] * 8
        + [p, p, p, p, p, ctypes.c_long, ctypes.c_long, p])
    lib.fgumi_ranges_equal.restype = None
    lib.fgumi_ranges_equal.argtypes = [p] * 5 + [ctypes.c_long, p]
    lib.fgumi_hash_ranges.restype = None
    lib.fgumi_hash_ranges.argtypes = [p, p, p, ctypes.c_long, p]
    lib.fgumi_template_coord_keys.restype = ctypes.c_long
    lib.fgumi_template_coord_keys.argtypes = (
        [p] * 15 + [ctypes.c_long, p, p])
    lib.fgumi_natural_name_keys.restype = ctypes.c_long
    lib.fgumi_natural_name_keys.argtypes = (
        [p] * 4 + [ctypes.c_long, p, p, p])
    lib.fgumi_unclipped_5prime.restype = None
    lib.fgumi_unclipped_5prime.argtypes = [p] * 5 + [ctypes.c_long, p]
    lib.fgumi_umi_scan.restype = None
    lib.fgumi_umi_scan.argtypes = [p, p, p, ctypes.c_long, p, p, p]
    lib.fgumi_rewrite_tag_records.restype = ctypes.c_long
    lib.fgumi_rewrite_tag_records.argtypes = (
        [p] * 4 + [ctypes.c_long, ctypes.c_ubyte, ctypes.c_ubyte]
        + [p] * 5)
    lib.fgumi_qual_scores.restype = None
    lib.fgumi_qual_scores.argtypes = (
        [p, p, p, ctypes.c_long, ctypes.c_int, ctypes.c_long, p])
    lib.fgumi_gather_u16_arrays.restype = None
    lib.fgumi_gather_u16_arrays.argtypes = (
        [p, p, ctypes.c_long, ctypes.c_long, p, p])
    lib.fgumi_apply_masks.restype = None
    lib.fgumi_apply_masks.argtypes = (
        [p, p, p, p, ctypes.c_long, p, ctypes.c_long, ctypes.c_int,
         p, p])
    lib.fgumi_rx_unanimous.restype = None
    lib.fgumi_rx_unanimous.argtypes = [p, p, p, p, ctypes.c_long, p, p]
    lib.fgumi_extract_records.restype = ctypes.c_long
    lib.fgumi_extract_records.argtypes = (
        [ctypes.c_long, ctypes.c_long] + [p] * 6 + [ctypes.c_long]
        + [p] * 3 + [ctypes.c_int, p, ctypes.c_int, ctypes.c_int, p,
                     ctypes.c_long, p])
    lib.fgumi_ref_spans.restype = None
    lib.fgumi_ref_spans.argtypes = [p, p, p, p, ctypes.c_long, p]
    lib.fgumi_concat_spans.restype = ctypes.c_long
    lib.fgumi_concat_spans.argtypes = [p, p, p, p, ctypes.c_long, p, p]
    lib.fgumi_tag_name_list.restype = None
    lib.fgumi_tag_name_list.argtypes = [p, p, p, ctypes.c_long,
                                        ctypes.c_long, p, p]
    lib.fgumi_cigar_strings.restype = ctypes.c_long
    lib.fgumi_cigar_strings.argtypes = [p, p, p, ctypes.c_long, p, p]
    lib.fgumi_rebuild_aux_records.restype = ctypes.c_long
    lib.fgumi_rebuild_aux_records.argtypes = [p] * 4 + [ctypes.c_long] \
        + [p] * 6
    lib.fgumi_bgzf_compress_many.restype = ctypes.c_long
    lib.fgumi_bgzf_compress_many.argtypes = [
        p, ctypes.c_long, ctypes.c_int, ctypes.c_int, p, ctypes.c_long,
        ctypes.c_long, p, ctypes.POINTER(ctypes.c_long)]
    lib.fgumi_sort_spans.restype = None
    lib.fgumi_sort_spans.argtypes = [p, p, p, ctypes.c_long, p]
    lib.fgumi_gather_spans.restype = ctypes.c_long
    lib.fgumi_gather_spans.argtypes = [p, p, p, p, ctypes.c_long, p]
    lib.fgumi_write_run.restype = ctypes.c_long
    lib.fgumi_write_run.argtypes = (
        [ctypes.c_char_p] + [p] * 7 + [ctypes.c_long, ctypes.c_long,
                                       ctypes.c_int])
    lib.fgumi_merge_open.restype = ctypes.c_void_p
    lib.fgumi_merge_open.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                     ctypes.c_long]
    lib.fgumi_merge_open2.restype = ctypes.c_void_p
    lib.fgumi_merge_open2.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                      ctypes.c_long, ctypes.c_int,
                                      ctypes.c_long]
    lib.fgumi_merge_next.restype = ctypes.c_long
    lib.fgumi_merge_next.argtypes = [
        ctypes.c_void_p, p, ctypes.c_long, p, ctypes.c_long,
        ctypes.POINTER(ctypes.c_long)]
    lib.fgumi_merge_close.restype = None
    lib.fgumi_merge_close.argtypes = [ctypes.c_void_p]



def get_lib():
    """The loaded native library, or None (pure-Python fallback)."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        if os.environ.get("FGUMI_TPU_NO_NATIVE"):
            _lib_failed = True
            return None
        def _abi_ok(candidate):
            # one copy of the versioned-ABI check (bumped in fgumi_native.cc
            # on any signature change), shared by the override and
            # cached-build paths
            if not hasattr(candidate, "fgumi_abi_version"):
                return False
            candidate.fgumi_abi_version.restype = ctypes.c_long
            return candidate.fgumi_abi_version() == _ABI_VERSION

        override = os.environ.get("FGUMI_TPU_NATIVE_SO")
        if override:
            # explicit prebuilt library (e.g. the ASAN/UBSAN test lane):
            # load it as-is — no rebuild fallback, loud failure
            try:
                lib = ctypes.CDLL(override)
            except OSError as e:
                log.warning("FGUMI_TPU_NATIVE_SO=%s failed to load: %s",
                            override, e)
                _lib_failed = True
                return None
            if not _abi_ok(lib):
                log.warning("FGUMI_TPU_NATIVE_SO=%s missing or mismatched "
                            "ABI (expected %d)", override, _ABI_VERSION)
                _lib_failed = True
                return None
            _declare(lib)
            _lib = lib
            return _lib
        if not os.path.exists(_SO_PATH) or (
                os.path.exists(_SRC_PATH)
                and os.path.getmtime(_SRC_PATH) > os.path.getmtime(_SO_PATH)):
            if not _build():
                _lib_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as e:
            log.debug("native library load failed: %s", e)
            _lib_failed = True
            return None
        # stale-.so guard: a cached build whose mtime ties the source (e.g.
        # archive extraction) passes the rebuild check but may predate newer
        # symbols OR carry old signatures; rebuild on ABI mismatch
        if not _abi_ok(lib):
            if not _build():
                _lib_failed = True
                return None
            try:
                lib = ctypes.CDLL(_SO_PATH)
            except OSError as e:
                log.debug("native library reload failed: %s", e)
                _lib_failed = True
                return None
            if not _abi_ok(lib):
                _lib_failed = True
                return None
        _declare(lib)
        _lib = lib
        log.debug("native library loaded from %s", _SO_PATH)
        return _lib


def bgzf_decompress(data, out_cap: int = None):
    """Decompress complete BGZF blocks from `data` (bytes/bytearray/view).

    Returns (decoded, consumed) or None when the native library is
    unavailable; `decoded` is a uint8 numpy array view over a fresh buffer
    (callers append it to their own buffers — returning bytes would add a
    full extra copy, and ctypes string buffers would add a zero-fill on top:
    both showed up as ~0.3s/stage on chain profiles). Raises ValueError on
    malformed input.
    """
    import numpy as np

    lib = get_lib()
    if lib is None:
        return None
    src = np.frombuffer(memoryview(data), dtype=np.uint8)  # zero-copy
    n = len(src)
    # Spec bound: each block is >=26 bytes and expands to at most 64 KiB, so
    # the true output can never exceed this cap. An ISIZE claiming more is
    # corrupt — the codec returns -2 and we report it rather than growing.
    max_cap = (n // 26 + 1) * (1 << 16)
    if out_cap is None:
        out_cap = min(max(4 * n + (1 << 16), 1 << 16), max_cap)
    out = np.empty(out_cap, dtype=np.uint8)
    consumed = ctypes.c_long(0)
    produced = lib.fgumi_bgzf_decompress(src.ctypes.data, n, out.ctypes.data,
                                         out_cap, ctypes.byref(consumed))
    # release the caller's buffer BEFORE any raise: a ValueError traceback
    # would otherwise pin this frame's view and turn the caller's recovery
    # (`self._raw.clear()` in BgzfReader._demote_to_zlib) into a BufferError
    src = None
    if produced == -2:
        if out_cap >= max_cap:
            raise ValueError("malformed BGZF block (ISIZE exceeds spec bound)")
        return bgzf_decompress(data, min(out_cap * 2, max_cap))
    if produced < 0:
        raise ValueError("malformed BGZF block")
    if out_cap - produced > produced // 2 + (1 << 20):
        # poorly-compressible input: a view would pin the 4x over-allocation
        # in callers that retain the chunk (batch_reader accumulation)
        return out[:produced].copy(), consumed.value
    return out[:produced], consumed.value


def gzip_decompress_all(data, max_out: int = None) -> "object":
    """Whole-buffer (multi-member) gzip decompression via libdeflate.

    Returns a uint8 numpy array; None when the native library is unavailable
    OR the output would exceed `max_out` (the caller's cue to stream with
    bounded memory instead — a highly compressible input can expand far past
    any compressed-size heuristic). Raises ValueError on malformed input.
    """
    import numpy as np

    lib = get_lib()
    if lib is None:
        return None
    src = np.frombuffer(memoryview(data), dtype=np.uint8)
    n = len(src)
    # seed the capacity from the ISIZE footer (uncompressed size of the
    # LAST member mod 2^32 — exact for the single-member files `gzip`
    # produces), so the common case never pays a wasted full decompression
    # before an INSUFFICIENT_SPACE retry; multi-member or lying footers
    # fall back to the retry loop
    isize = int.from_bytes(bytes(src[-4:]), "little") if n >= 18 else 0
    # clamp the footer-seeded guess to a sane expansion ratio: a corrupt or
    # truncated footer is arbitrary bytes and must not size the allocation
    cap = max(min(isize + 64, 1024 * n), 4 * n, 1 << 16)
    # hard retry ceiling even without an explicit max_out: deflate expands
    # at most ~1032x, so a crafted multi-member stream with lying ISIZE
    # footers cannot drive the doubling loop to MemoryError (ADVICE r4)
    hard_cap = 1040 * n + (1 << 16)
    max_out = hard_cap if max_out is None else min(max_out, hard_cap)
    cap = min(cap, max_out)
    while True:
        out = np.empty(cap, dtype=np.uint8)
        produced = lib.fgumi_gzip_decompress(src.ctypes.data, n,
                                             out.ctypes.data, cap)
        if produced == -2:
            if cap >= max_out:
                return None  # too big to materialize: stream instead
            cap = min(cap * 2, max_out)
            continue
        src = None
        data = None
        if produced < 0:
            raise ValueError("malformed gzip stream")
        if cap - produced > (32 << 20):
            # a view would pin the whole over-allocation for the stream's
            # lifetime; copy down when the slack is significant
            return out[:produced].copy()
        return out[:produced]


def zlib_compress(data: bytes, level: int = 1):
    """zlib-format compression via libdeflate, or None (fallback to zlib)."""
    lib = get_lib()
    if lib is None:
        return None
    cap = len(data) + len(data) // 8 + 256
    out = ctypes.create_string_buffer(cap)
    n = lib.fgumi_zlib_compress(bytes(data), len(data), level, out, cap)
    if n < 0:
        raise ValueError("zlib compression failed")
    return out.raw[:n]


def zlib_decompress(data: bytes, out_size: int):
    """Decompress a zlib-format buffer of known output size, or None."""
    lib = get_lib()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(out_size)
    n = lib.fgumi_zlib_decompress(bytes(data), len(data), out, out_size)
    if n < 0:
        raise ValueError("malformed zlib frame")
    return out.raw[:n]


_COMPRESS_THREADS = None


def compress_threads() -> int:
    """Worker threads for multi-block BGZF compression. Default: min(4,
    cpus//2) — enough to keep the writer off the critical path without
    oversubscribing XLA's pool; override with FGUMI_TPU_COMPRESS_THREADS."""
    global _COMPRESS_THREADS
    if _COMPRESS_THREADS is None:
        env = os.environ.get("FGUMI_TPU_COMPRESS_THREADS", "")
        if env.isdigit():
            _COMPRESS_THREADS = max(int(env), 1)
        else:
            _COMPRESS_THREADS = max(min(4, (os.cpu_count() or 2) // 2), 1)
    return _COMPRESS_THREADS


def bgzf_compress_many(data, level: int = 1, threads: int = None):
    """Compress `data` into consecutive complete BGZF blocks (one native
    call, optionally multi-threaded). Returns the block stream bytes and the
    (n_blocks+1,) int64 block-offset table, or None (fallback)."""
    import numpy as np

    lib = get_lib()
    if lib is None:
        return None
    if threads is None:
        threads = compress_threads()
    src = np.frombuffer(memoryview(data), dtype=np.uint8)  # zero-copy
    n = len(src)
    n_blocks = (n + 0xFEFF) // 0xFF00
    bound = 0xFF00 + (0xFF00 >> 2) + 64  # >= deflate bound + BGZF framing
    out = np.empty(max(n_blocks, 1) * bound, dtype=np.uint8)
    block_off = np.empty(n_blocks + 1, dtype=np.int64)
    n_out = ctypes.c_long(0)
    total = lib.fgumi_bgzf_compress_many(
        src.ctypes.data, n, level, threads, out.ctypes.data, len(out), bound,
        block_off.ctypes.data, ctypes.byref(n_out))
    # release the caller's buffer before any raise (see bgzf_decompress) —
    # including `data` itself, which is typically the caller's memoryview
    # export over a bytearray it will resize during cleanup
    src = None
    data = None
    if total < 0:
        raise ValueError("BGZF multi-block compression failed")
    # a view, not .tobytes(): callers hand it straight to file.write()
    return out[:total], block_off


def bgzf_compress_block(data: bytes, level: int = 1):
    """One BGZF block for <=0xFF00 input bytes, or None (fallback)."""
    lib = get_lib()
    if lib is None:
        return None
    cap = len(data) + (1 << 12) + 64
    out = ctypes.create_string_buffer(cap)
    size = lib.fgumi_bgzf_compress_block(bytes(data), len(data), level, out,
                                         cap)
    if size < 0:
        raise ValueError("BGZF block compression failed")
    return out.raw[:size]
