// Native runtime hot paths for fgumi-tpu.
//
// C++ equivalents of the reference's native Rust layers (SURVEY.md §2 intro):
// BGZF block codec on libdeflate (reference: crates/fgumi-bgzf/src/lib.rs —
// libdeflater block read/decompress + InlineBgzfCompressor) and BAM record
// boundary scanning (reference: src/lib/unified_pipeline/bam.rs FindBoundaries).
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).

#include <libdeflate.h>

#include <cstdint>
#include <cstring>

namespace {

thread_local libdeflate_decompressor* tls_decompressor = nullptr;
thread_local libdeflate_compressor* tls_compressor = nullptr;
thread_local int tls_compressor_level = -1;

libdeflate_decompressor* decompressor() {
  if (tls_decompressor == nullptr) {
    tls_decompressor = libdeflate_alloc_decompressor();
  }
  return tls_decompressor;
}

libdeflate_compressor* compressor(int level) {
  if (tls_compressor == nullptr || tls_compressor_level != level) {
    if (tls_compressor != nullptr) {
      libdeflate_free_compressor(tls_compressor);
    }
    tls_compressor = libdeflate_alloc_compressor(level);
    tls_compressor_level = level;
  }
  return tls_compressor;
}

inline uint16_t read_u16(const uint8_t* p) {
  return static_cast<uint16_t>(p[0]) | (static_cast<uint16_t>(p[1]) << 8);
}

inline uint32_t read_u32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

// Parse one BGZF block header at src[0..len): returns the total block size
// (BSIZE+1) and sets *data_off to the deflate payload offset, or 0 on
// malformed / truncated header. BGZF = gzip member with an FEXTRA "BC"
// subfield carrying BSIZE (SAM spec §4.1).
long parse_bgzf_header(const uint8_t* src, long len, long* data_off) {
  if (len < 18) return 0;
  if (src[0] != 0x1F || src[1] != 0x8B || src[2] != 0x08 ||
      (src[3] & 0x04) == 0) {
    return 0;
  }
  const uint16_t xlen = read_u16(src + 10);
  if (12 + static_cast<long>(xlen) > len) return 0;
  long off = 12;
  const long extra_end = 12 + xlen;
  long bsize = -1;
  while (off + 4 <= extra_end) {
    const uint8_t si1 = src[off];
    const uint8_t si2 = src[off + 1];
    const uint16_t slen = read_u16(src + off + 2);
    if (si1 == 0x42 && si2 == 0x43 && slen == 2 && off + 6 <= extra_end) {
      bsize = static_cast<long>(read_u16(src + off + 4)) + 1;
    }
    off += 4 + slen;
  }
  if (bsize < 0) return 0;
  *data_off = extra_end;
  return bsize;
}

}  // namespace

extern "C" {

// Decompress as many complete BGZF blocks from src as fit in dst.
// Returns bytes produced; sets *consumed to the input bytes consumed (whole
// blocks only — a trailing partial block is left for the caller's next call).
// Returns -1 on a malformed block, -2 when dst has no room for the next
// block's payload (caller grows dst or flushes first).
long fgumi_bgzf_decompress(const uint8_t* src, long src_len, uint8_t* dst,
                           long dst_cap, long* consumed) {
  long in_off = 0;
  long out_off = 0;
  while (in_off < src_len) {
    long data_off = 0;
    const long bsize = parse_bgzf_header(src + in_off, src_len - in_off,
                                         &data_off);
    if (bsize == 0) {
      // either truncated (partial tail) or malformed; distinguish by whether
      // at least a full header could have been present
      if (src_len - in_off >= 18 &&
          (src[in_off] != 0x1F || src[in_off + 1] != 0x8B)) {
        if (out_off == 0 && in_off == 0) return -1;
      }
      break;  // partial block: wait for more input
    }
    if (in_off + bsize > src_len) break;  // partial block
    const uint8_t* payload = src + in_off + data_off;
    const long payload_len = bsize - data_off - 8;
    if (payload_len < 0) return -1;
    const uint32_t isize = read_u32(src + in_off + bsize - 4);
    if (isize > 0x10000) return -1;  // a BGZF block holds at most 64 KiB
    if (static_cast<long>(isize) > dst_cap - out_off) {
      if (out_off == 0) return -2;
      break;  // no room: return what we have
    }
    size_t actual = 0;
    const libdeflate_result r = libdeflate_deflate_decompress(
        decompressor(), payload, static_cast<size_t>(payload_len),
        dst + out_off, static_cast<size_t>(isize), &actual);
    if (r != LIBDEFLATE_SUCCESS || actual != isize) return -1;
    out_off += static_cast<long>(isize);
    in_off += bsize;
  }
  *consumed = in_off;
  return out_off;
}

// Compress src (<= 0xFF00 bytes) into one complete BGZF block at dst.
// Returns the block size, or -1 on failure / insufficient dst capacity.
long fgumi_bgzf_compress_block(const uint8_t* src, long src_len, int level,
                               uint8_t* dst, long dst_cap) {
  if (src_len > 0xFF00 || dst_cap < 64) return -1;
  static const uint8_t header[18] = {
      0x1F, 0x8B, 0x08, 0x04, 0, 0, 0, 0, 0, 0xFF,  // gzip, FEXTRA, OS=unknown
      6,    0,                                       // XLEN
      0x42, 0x43, 2, 0,                              // "BC", SLEN=2
      0,    0,                                       // BSIZE placeholder
  };
  std::memcpy(dst, header, 18);
  const size_t cap = static_cast<size_t>(dst_cap) - 18 - 8;
  size_t payload = libdeflate_deflate_compress(
      compressor(level), src, static_cast<size_t>(src_len), dst + 18, cap);
  if (payload == 0) return -1;  // didn't fit
  const long bsize = static_cast<long>(payload) + 18 + 8;
  if (bsize > 0x10000) return -1;
  dst[16] = static_cast<uint8_t>((bsize - 1) & 0xFF);
  dst[17] = static_cast<uint8_t>(((bsize - 1) >> 8) & 0xFF);
  const uint32_t crc = libdeflate_crc32(0, src, static_cast<size_t>(src_len));
  uint8_t* tail = dst + 18 + payload;
  tail[0] = crc & 0xFF;
  tail[1] = (crc >> 8) & 0xFF;
  tail[2] = (crc >> 16) & 0xFF;
  tail[3] = (crc >> 24) & 0xFF;
  const uint32_t isize = static_cast<uint32_t>(src_len);
  tail[4] = isize & 0xFF;
  tail[5] = (isize >> 8) & 0xFF;
  tail[6] = (isize >> 16) & 0xFF;
  tail[7] = (isize >> 24) & 0xFF;
  return bsize;
}

// Scan decoded BAM bytes for record boundaries: offsets[i] = start of record i
// (the 4-byte block_size prefix). Returns the number of complete records
// found; sets *scanned to the byte offset just past the last complete record.
// Mirrors the FindBoundaries step (unified_pipeline/bam.rs:180).
long fgumi_find_record_boundaries(const uint8_t* buf, long len,
                                  int64_t* offsets, long max_records,
                                  int64_t* scanned) {
  long off = 0;
  long n = 0;
  while (off + 4 <= len && n < max_records) {
    const uint32_t block_size = read_u32(buf + off);
    if (off + 4 + static_cast<long>(block_size) > len) break;
    offsets[n++] = off;
    off += 4 + static_cast<long>(block_size);
  }
  *scanned = off;
  return n;
}

}  // extern "C"
