// Native runtime hot paths for fgumi-tpu.
//
// C++ equivalents of the reference's native Rust layers (SURVEY.md §2 intro):
// BGZF block codec on libdeflate (reference: crates/fgumi-bgzf/src/lib.rs —
// libdeflater block read/decompress + InlineBgzfCompressor) and BAM record
// boundary scanning (reference: src/lib/unified_pipeline/bam.rs FindBoundaries).
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).

#include <libdeflate.h>

#include <algorithm>
#include <cerrno>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <utility>
#include <vector>

namespace {

thread_local libdeflate_decompressor* tls_decompressor = nullptr;
thread_local libdeflate_compressor* tls_compressor = nullptr;
thread_local int tls_compressor_level = -1;

libdeflate_decompressor* decompressor() {
  if (tls_decompressor == nullptr) {
    tls_decompressor = libdeflate_alloc_decompressor();
  }
  return tls_decompressor;
}

libdeflate_compressor* compressor(int level) {
  if (tls_compressor == nullptr || tls_compressor_level != level) {
    if (tls_compressor != nullptr) {
      libdeflate_free_compressor(tls_compressor);
    }
    tls_compressor = libdeflate_alloc_compressor(level);
    tls_compressor_level = level;
  }
  return tls_compressor;
}

inline uint16_t read_u16(const uint8_t* p) {
  return static_cast<uint16_t>(p[0]) | (static_cast<uint16_t>(p[1]) << 8);
}

inline uint32_t read_u32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

// Parse one BGZF block header at src[0..len): returns the total block size
// (BSIZE+1) and sets *data_off to the deflate payload offset, or 0 on
// malformed / truncated header. BGZF = gzip member with an FEXTRA "BC"
// subfield carrying BSIZE (SAM spec §4.1).
long parse_bgzf_header(const uint8_t* src, long len, long* data_off) {
  if (len < 18) return 0;
  if (src[0] != 0x1F || src[1] != 0x8B || src[2] != 0x08 ||
      (src[3] & 0x04) == 0) {
    return 0;
  }
  const uint16_t xlen = read_u16(src + 10);
  if (12 + static_cast<long>(xlen) > len) return 0;
  long off = 12;
  const long extra_end = 12 + xlen;
  long bsize = -1;
  while (off + 4 <= extra_end) {
    const uint8_t si1 = src[off];
    const uint8_t si2 = src[off + 1];
    const uint16_t slen = read_u16(src + off + 2);
    if (si1 == 0x42 && si2 == 0x43 && slen == 2 && off + 6 <= extra_end) {
      bsize = static_cast<long>(read_u16(src + off + 4)) + 1;
    }
    off += 4 + slen;
  }
  if (bsize < 0) return 0;
  *data_off = extra_end;
  return bsize;
}

}  // namespace

extern "C" {

// ABI version for the stale-.so guard in __init__.py: bump whenever any
// exported signature changes (a symbol probe alone cannot detect an
// argument-list change in an existing function).
long fgumi_abi_version() { return 14; }

// Candidate UMI pairs with hamming(A[i], B[j]) <= d over (n, L)/(m, L) byte
// matrices, via the d+1-part pigeonhole (umi/assigners.py
// _pigeonhole_pairs, reference BK-tree/n-gram analog): any pair within
// distance d agrees exactly on at least one of d+1 disjoint column chunks.
// B == A (same pointer) emits each unordered pair once (i < j); otherwise
// all cross pairs with i != j. First-matching-part dedup keeps the output
// duplicate-free. Returns the pair count; only the first `cap` pairs are
// written (caller retries with a larger buffer when count > cap).
long fgumi_umi_neighbor_pairs(const uint8_t* A, long n, const uint8_t* B,
                              long m, long L, int d, int64_t* out_i,
                              int64_t* out_j, long cap) {
  const bool same = (A == B);
  const int parts = d + 1 <= static_cast<int>(L) ? d + 1 : static_cast<int>(L);
  if (parts <= 0) return 0;
  // np.array_split sizing: first (L % parts) chunks get one extra column
  std::vector<long> p_lo(static_cast<size_t>(parts) + 1, 0);
  {
    const long base = L / parts;
    const long extra = L % parts;
    for (int p = 0; p < parts; ++p) {
      p_lo[static_cast<size_t>(p) + 1] =
          p_lo[static_cast<size_t>(p)] + base + (p < extra ? 1 : 0);
    }
  }
  auto ham_le = [&](const uint8_t* a, const uint8_t* b) {
    int miss = 0;
    for (long c = 0; c < L; ++c) {
      miss += (a[c] != b[c]);
      if (miss > d) return false;
    }
    return true;
  };
  auto chunk_eq = [&](const uint8_t* a, const uint8_t* b, int p) {
    return std::memcmp(a + p_lo[static_cast<size_t>(p)],
                       b + p_lo[static_cast<size_t>(p)],
                       static_cast<size_t>(p_lo[static_cast<size_t>(p) + 1] -
                                           p_lo[static_cast<size_t>(p)])) == 0;
  };
  long count = 0;
  auto emit = [&](long i, long j) {
    if (count < cap) {
      out_i[count] = i;
      out_j[count] = j;
    }
    ++count;
  };
  std::vector<int64_t> ob(static_cast<size_t>(m));
  std::vector<int64_t> oa;
  for (int p = 0; p < parts; ++p) {
    const long clo = p_lo[static_cast<size_t>(p)];
    const long clen = p_lo[static_cast<size_t>(p) + 1] - clo;
    for (long r = 0; r < m; ++r) ob[static_cast<size_t>(r)] = r;
    auto key_less = [&](int64_t x, int64_t y) {
      const int c = std::memcmp(B + x * L + clo, B + y * L + clo,
                                static_cast<size_t>(clen));
      return c < 0 || (c == 0 && x < y);
    };
    std::sort(ob.begin(), ob.end(), key_less);
    if (same) {
      for (long s = 0; s < m;) {
        long e = s + 1;
        while (e < m && std::memcmp(B + ob[static_cast<size_t>(s)] * L + clo,
                                    B + ob[static_cast<size_t>(e)] * L + clo,
                                    static_cast<size_t>(clen)) == 0) {
          ++e;
        }
        for (long x = s; x < e; ++x) {
          for (long y = x + 1; y < e; ++y) {
            const long i = static_cast<long>(ob[static_cast<size_t>(x)]);
            const long j = static_cast<long>(ob[static_cast<size_t>(y)]);
            const uint8_t* ra = A + i * L;
            const uint8_t* rb = A + j * L;
            if (!ham_le(ra, rb)) continue;
            bool seen = false;
            for (int q = 0; q < p; ++q) {
              if (chunk_eq(ra, rb, q)) {
                seen = true;
                break;
              }
            }
            if (!seen) emit(i < j ? i : j, i < j ? j : i);
          }
        }
        s = e;
      }
    } else {
      // cross case (paired-UMI reversal): bucket B, probe with each A row
      oa.resize(static_cast<size_t>(n));
      for (long r = 0; r < n; ++r) oa[static_cast<size_t>(r)] = r;
      auto akey_less = [&](int64_t x, int64_t y) {
        const int c = std::memcmp(A + x * L + clo, A + y * L + clo,
                                  static_cast<size_t>(clen));
        return c < 0 || (c == 0 && x < y);
      };
      std::sort(oa.begin(), oa.end(), akey_less);
      long bs = 0;
      for (long as = 0; as < n;) {
        long ae = as + 1;
        const uint8_t* akey = A + oa[static_cast<size_t>(as)] * L + clo;
        while (ae < n && std::memcmp(akey,
                                     A + oa[static_cast<size_t>(ae)] * L + clo,
                                     static_cast<size_t>(clen)) == 0) {
          ++ae;
        }
        while (bs < m && std::memcmp(B + ob[static_cast<size_t>(bs)] * L + clo,
                                     akey,
                                     static_cast<size_t>(clen)) < 0) {
          ++bs;
        }
        long be = bs;
        while (be < m && std::memcmp(B + ob[static_cast<size_t>(be)] * L + clo,
                                     akey,
                                     static_cast<size_t>(clen)) == 0) {
          ++be;
        }
        for (long x = as; x < ae; ++x) {
          for (long y = bs; y < be; ++y) {
            const long i = static_cast<long>(oa[static_cast<size_t>(x)]);
            const long j = static_cast<long>(ob[static_cast<size_t>(y)]);
            if (i == j) continue;
            const uint8_t* ra = A + i * L;
            const uint8_t* rb = B + j * L;
            if (!ham_le(ra, rb)) continue;
            bool seen = false;
            for (int q = 0; q < p; ++q) {
              if (chunk_eq(ra, rb, q)) {
                seen = true;
                break;
              }
            }
            if (!seen) emit(i, j);
          }
        }
        as = ae;
      }
    }
  }
  return count;
}

// BK-tree candidate search over fixed-length byte UMIs (Hamming metric) —
// the reference's second index flavor (assigner.rs:228,267) beside the
// pigeonhole partition search above. Children prune by the triangle
// inequality |dist(child) - dist(query, node)| <= d. Measured (see
// native/batch.py umi_neighbor_pairs): at UMI lengths 8-12 the pigeonhole
// wins 3-6x at every d=1..4 — short random UMIs sit near distance 0.75*L,
// so the triangle bound prunes little — hence this is the verification
// alternative (FGUMI_TPU_UMI_INDEX=bktree), not the default.
// Same output contract as fgumi_umi_neighbor_pairs: unique pairs with
// hamming <= d; A == B emits i < j once, otherwise (A row, B row) cross
// pairs with i == j skipped. The tree is built over B; A rows query it.
long fgumi_umi_bktree_pairs(const uint8_t* A, long n, const uint8_t* B,
                            long m, long L, int d, int64_t* out_i,
                            int64_t* out_j, long cap) {
  if (m <= 0 || n <= 0 || L <= 0) return 0;  // L==0: match pigeonhole
  const bool same = (A == B);
  std::vector<long> first_child(static_cast<size_t>(m), -1);
  std::vector<long> next_sib(static_cast<size_t>(m), -1);
  std::vector<int> cdist(static_cast<size_t>(m), 0);
  auto ham = [&](const uint8_t* a, const uint8_t* b) {
    int miss = 0;
    for (long c = 0; c < L; ++c) miss += (a[c] != b[c]);
    return miss;
  };
  long count = 0;
  auto emit = [&](long i, long j) {
    if (count < cap) {
      out_i[count] = i;
      out_j[count] = j;
    }
    ++count;
  };
  auto insert = [&](long v) {  // v > 0; root is row 0 of B
    long u = 0;
    for (;;) {
      const int duv = ham(B + u * L, B + v * L);
      long c = first_child[static_cast<size_t>(u)];
      while (c != -1 && cdist[static_cast<size_t>(c)] != duv) {
        c = next_sib[static_cast<size_t>(c)];
      }
      if (c == -1) {
        cdist[static_cast<size_t>(v)] = duv;
        next_sib[static_cast<size_t>(v)] =
            first_child[static_cast<size_t>(u)];
        first_child[static_cast<size_t>(u)] = v;
        return;
      }
      u = c;
    }
  };
  std::vector<long> stack;
  auto query = [&](const uint8_t* q, long tree_hi, long qi, bool as_same) {
    // all tree nodes u < tree_hi with hamming(q, B[u]) <= d
    stack.clear();
    stack.push_back(0);
    while (!stack.empty()) {
      const long u = stack.back();
      stack.pop_back();
      const int duq = ham(B + u * L, q);
      if (duq <= d && u != qi) {  // u == qi: self (same) / same-template
        if (as_same) {            // (cross, pigeonhole i == j contract)
          emit(u < qi ? u : qi, u < qi ? qi : u);
        } else {
          emit(qi, u);
        }
      }
      for (long c = first_child[static_cast<size_t>(u)]; c != -1;
           c = next_sib[static_cast<size_t>(c)]) {
        if (c >= tree_hi) continue;  // not yet inserted (same-matrix mode)
        const int cd = cdist[static_cast<size_t>(c)];
        if (cd >= duq - d && cd <= duq + d) stack.push_back(c);
      }
    }
  };
  if (same) {
    // incremental: query the tree of rows < v, then insert v — each
    // unordered pair is found exactly once
    for (long v = 1; v < m; ++v) {
      query(B + v * L, v, v, true);
      insert(v);
    }
  } else {
    for (long v = 1; v < m; ++v) insert(v);
    for (long i = 0; i < n; ++i) query(A + i * L, m, i, false);
  }
  return count;
}

// UMI-tools directed adjacency BFS over flattened neighbor lists
// (umi/assigners.py _adjacency_bfs; reference assigner.rs:1480-1548).
// Nodes are pre-sorted by (-count, string); neighbors(i) =
// nbr_flat[nbr_start[i]:nbr_start[i+1]] in ascending order. root_of[i]
// receives the component root index.
void fgumi_adjacency_bfs(const int64_t* nbr_flat, const int64_t* nbr_start,
                         const int64_t* counts, long n, int64_t* root_of) {
  std::vector<uint8_t> assigned(static_cast<size_t>(n), 0);
  std::vector<int64_t> queue;
  queue.reserve(64);
  for (long root = 0; root < n; ++root) {
    if (assigned[static_cast<size_t>(root)]) continue;
    assigned[static_cast<size_t>(root)] = 1;
    root_of[root] = root;
    queue.clear();
    queue.push_back(root);
    size_t head = 0;
    while (head < queue.size()) {
      const int64_t idx = queue[head++];
      const int64_t max_child = counts[idx] / 2 + 1;
      for (int64_t t = nbr_start[idx]; t < nbr_start[idx + 1]; ++t) {
        const int64_t child = nbr_flat[t];
        if (!assigned[static_cast<size_t>(child)] &&
            counts[child] <= max_child) {
          assigned[static_cast<size_t>(child)] = 1;
          root_of[child] = root_of[idx];
          queue.push_back(child);
        }
      }
    }
  }
}

// Decompress a whole (possibly multi-member) plain-gzip buffer with
// libdeflate. Streaming inflate (zlib) runs ~180 MB/s on the bench host;
// libdeflate's whole-member path runs ~2-3x that, which matters because
// gzip FASTQ is the entry point of the best-practice chain. Returns bytes
// produced, -1 malformed, -2 when dst is too small (caller retries larger).
long fgumi_gzip_decompress(const uint8_t* src, long n, uint8_t* dst,
                           long cap) {
  libdeflate_decompressor* d = decompressor();
  long in_off = 0;
  long out_off = 0;
  while (in_off < n) {
    size_t a_in = 0;
    size_t a_out = 0;
    enum libdeflate_result r = libdeflate_gzip_decompress_ex(
        d, src + in_off, static_cast<size_t>(n - in_off), dst + out_off,
        static_cast<size_t>(cap - out_off), &a_in, &a_out);
    if (r == LIBDEFLATE_INSUFFICIENT_SPACE) return -2;
    if (r != LIBDEFLATE_SUCCESS) return -1;
    in_off += static_cast<long>(a_in);
    out_off += static_cast<long>(a_out);
    if (a_in == 0) break;  // defensive: no forward progress
  }
  return out_off;
}

// Decompress as many complete BGZF blocks from src as fit in dst.
// Returns bytes produced; sets *consumed to the input bytes consumed (whole
// blocks only — a trailing partial block is left for the caller's next call).
// Returns -1 on a malformed block, -2 when dst has no room for the next
// block's payload (caller grows dst or flushes first).
long fgumi_bgzf_decompress(const uint8_t* src, long src_len, uint8_t* dst,
                           long dst_cap, long* consumed) {
  long in_off = 0;
  long out_off = 0;
  while (in_off < src_len) {
    long data_off = 0;
    const long bsize = parse_bgzf_header(src + in_off, src_len - in_off,
                                         &data_off);
    if (bsize == 0) {
      // either truncated (partial tail) or malformed; distinguish by whether
      // at least a full header could have been present
      if (src_len - in_off >= 18 &&
          (src[in_off] != 0x1F || src[in_off + 1] != 0x8B)) {
        if (out_off == 0 && in_off == 0) return -1;
      }
      break;  // partial block: wait for more input
    }
    if (in_off + bsize > src_len) break;  // partial block
    const uint8_t* payload = src + in_off + data_off;
    const long payload_len = bsize - data_off - 8;
    if (payload_len < 0) return -1;
    const uint32_t isize = read_u32(src + in_off + bsize - 4);
    if (isize > 0x10000) return -1;  // a BGZF block holds at most 64 KiB
    if (static_cast<long>(isize) > dst_cap - out_off) {
      if (out_off == 0) return -2;
      break;  // no room: return what we have
    }
    size_t actual = 0;
    const libdeflate_result r = libdeflate_deflate_decompress(
        decompressor(), payload, static_cast<size_t>(payload_len),
        dst + out_off, static_cast<size_t>(isize), &actual);
    if (r != LIBDEFLATE_SUCCESS || actual != isize) return -1;
    out_off += static_cast<long>(isize);
    in_off += bsize;
  }
  *consumed = in_off;
  return out_off;
}

// zlib-format whole-buffer codec (sort spill frames; the reference uses
// zstd-1 for the same role, codec.rs:7-8 — libdeflate level 1 is the
// closest native analog available here, ~2-4x Python zlib).
long fgumi_zlib_compress(const uint8_t* src, long src_len, int level,
                         uint8_t* dst, long dst_cap) {
  const size_t n = libdeflate_zlib_compress(
      compressor(level), src, static_cast<size_t>(src_len), dst,
      static_cast<size_t>(dst_cap));
  return n == 0 ? -1 : static_cast<long>(n);
}

long fgumi_zlib_decompress(const uint8_t* src, long src_len, uint8_t* dst,
                           long dst_cap) {
  size_t actual = 0;
  const libdeflate_result r = libdeflate_zlib_decompress(
      decompressor(), src, static_cast<size_t>(src_len), dst,
      static_cast<size_t>(dst_cap), &actual);
  return r == LIBDEFLATE_SUCCESS ? static_cast<long>(actual) : -1;
}

// Compress src (<= 0xFF00 bytes) into one complete BGZF block at dst.
// Returns the block size, or -1 on failure / insufficient dst capacity.
long fgumi_bgzf_compress_block(const uint8_t* src, long src_len, int level,
                               uint8_t* dst, long dst_cap) {
  if (src_len > 0xFF00 || dst_cap < 64) return -1;
  static const uint8_t header[18] = {
      0x1F, 0x8B, 0x08, 0x04, 0, 0, 0, 0, 0, 0xFF,  // gzip, FEXTRA, OS=unknown
      6,    0,                                       // XLEN
      0x42, 0x43, 2, 0,                              // "BC", SLEN=2
      0,    0,                                       // BSIZE placeholder
  };
  std::memcpy(dst, header, 18);
  const size_t cap = static_cast<size_t>(dst_cap) - 18 - 8;
  size_t payload = libdeflate_deflate_compress(
      compressor(level), src, static_cast<size_t>(src_len), dst + 18, cap);
  if (payload == 0) return -1;  // didn't fit
  const long bsize = static_cast<long>(payload) + 18 + 8;
  if (bsize > 0x10000) return -1;
  dst[16] = static_cast<uint8_t>((bsize - 1) & 0xFF);
  dst[17] = static_cast<uint8_t>(((bsize - 1) >> 8) & 0xFF);
  const uint32_t crc = libdeflate_crc32(0, src, static_cast<size_t>(src_len));
  uint8_t* tail = dst + 18 + payload;
  tail[0] = crc & 0xFF;
  tail[1] = (crc >> 8) & 0xFF;
  tail[2] = (crc >> 16) & 0xFF;
  tail[3] = (crc >> 24) & 0xFF;
  const uint32_t isize = static_cast<uint32_t>(src_len);
  tail[4] = isize & 0xFF;
  tail[5] = (isize >> 8) & 0xFF;
  tail[6] = (isize >> 16) & 0xFF;
  tail[7] = (isize >> 24) & 0xFF;
  return bsize;
}

// Scan decoded BAM bytes for record boundaries: offsets[i] = start of record i
// (the 4-byte block_size prefix). Returns the number of complete records
// found; sets *scanned to the byte offset just past the last complete record.
// Mirrors the FindBoundaries step (unified_pipeline/bam.rs:180).
long fgumi_find_record_boundaries(const uint8_t* buf, long len,
                                  int64_t* offsets, long max_records,
                                  int64_t* scanned) {
  long off = 0;
  long n = 0;
  while (off + 4 <= len && n < max_records) {
    const uint32_t block_size = read_u32(buf + off);
    if (off + 4 + static_cast<long>(block_size) > len) break;
    offsets[n++] = off;
    off += 4 + static_cast<long>(block_size);
  }
  *scanned = off;
  return n;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Batch consensus-record serializer.
// ---------------------------------------------------------------------------

namespace {

// consensus base code -> BAM seq nibble (A,C,G,T,N -> 1,2,4,8,15).
const uint8_t kCode2Nib[5] = {1, 2, 4, 8, 15};

inline void put_u16(uint8_t* p, uint16_t v) {
  p[0] = v & 0xFF;
  p[1] = v >> 8;
}

inline void put_u32(uint8_t* p, uint32_t v) {
  p[0] = v & 0xFF;
  p[1] = (v >> 8) & 0xFF;
  p[2] = (v >> 16) & 0xFF;
  p[3] = (v >> 24) & 0xFF;
}

}  // namespace

extern "C" {

// Serialize J unmapped consensus records (block_size-prefixed BAM wire bytes)
// into `out`. Mirrors VanillaConsensusCaller._build_record
// (consensus/vanilla.py:439-483; reference build_consensus_record_into,
// vanilla_caller.rs:1452-1540): header + name + packed seq + quals, then tags
// RG:Z, cD:i, cM:i, cE:f, [cd:B:s, ce:B:s], MI:Z, [RX:Z]. depth/errors clamp
// to i16::MAX (fgbio Short semantics). Names are prefix + ':' + MI value.
// Per-record data arrives as raw addresses (code_addr[j] -> uint8[lens[j]],
// depth_addr[j] -> int32[lens[j]], ...) so callers can point straight into
// their bucket tensors without gathering a dense (J, L) copy. MI/RX values
// are absolute addresses too (mi_addr[j] -> uint8[mi_len[j]]) so they can
// reference the decoded batch buffer directly (no per-job gather blob);
// rx_addr[j] == 0 marks an absent RX tag.
// Returns total bytes written, or -1 when out_cap is insufficient.
long fgumi_build_consensus_records(
    const int64_t* code_addr, const int64_t* qual_addr,
    const int64_t* depth_addr, const int64_t* err_addr, const int32_t* lens,
    const int32_t* flags, long J, const uint8_t* prefix, int prefix_len,
    const int64_t* mi_addr, const int32_t* mi_len,
    const int64_t* rx_addr, const int32_t* rx_len,
    const uint8_t* rg, int rg_len, int per_base_tags, uint8_t* out,
    long out_cap, int64_t* rec_end) {
  long off = 0;
  for (long j = 0; j < J; ++j) {
    const int32_t L = lens[j];
    const uint8_t* crow = reinterpret_cast<const uint8_t*>(code_addr[j]);
    const uint8_t* qrow = reinterpret_cast<const uint8_t*>(qual_addr[j]);
    const int32_t* drow = reinterpret_cast<const int32_t*>(depth_addr[j]);
    const int32_t* erow = reinterpret_cast<const int32_t*>(err_addr[j]);
    const int32_t name_len = prefix_len + 1 + mi_len[j];
    if (name_len + 1 > 255) return -2;  // l_read_name is a u8 (caller raises)
    long need = 4 + 32 + name_len + 1 + (L + 1) / 2 + L;
    need += 3 + rg_len + 1;        // RG:Z
    need += (7 + 7 + 7);           // cD cM cE
    if (per_base_tags) need += 2 * (8 + 2 * static_cast<long>(L));
    need += 3 + mi_len[j] + 1;     // MI:Z
    if (rx_addr[j] != 0) need += 3 + rx_len[j] + 1;
    if (off + need > out_cap) return -1;
    const uint8_t* mi_p = reinterpret_cast<const uint8_t*>(mi_addr[j]);

    uint8_t* rec = out + off + 4;  // past block_size prefix
    // fixed header (io/bam.py start_unmapped): refID -1, pos -1, l_read_name,
    // mapq 0, bin 4680, n_cigar 0, flag, l_seq, next_refID -1, next_pos -1,
    // tlen 0
    put_u32(rec + 0, 0xFFFFFFFFu);
    put_u32(rec + 4, 0xFFFFFFFFu);
    rec[8] = static_cast<uint8_t>(name_len + 1);
    rec[9] = 0;
    put_u16(rec + 10, 4680);
    put_u16(rec + 12, 0);
    put_u16(rec + 14, static_cast<uint16_t>(flags[j]));
    put_u32(rec + 16, static_cast<uint32_t>(L));
    put_u32(rec + 20, 0xFFFFFFFFu);
    put_u32(rec + 24, 0xFFFFFFFFu);
    put_u32(rec + 28, 0);
    uint8_t* p = rec + 32;
    std::memcpy(p, prefix, static_cast<size_t>(prefix_len));
    p += prefix_len;
    *p++ = ':';
    std::memcpy(p, mi_p, static_cast<size_t>(mi_len[j]));
    p += mi_len[j];
    *p++ = 0;
    // packed seq
    for (int32_t i = 0; i + 1 < L; i += 2) {
      const uint8_t hi = kCode2Nib[crow[i] < 4 ? crow[i] : 4];
      const uint8_t lo = kCode2Nib[crow[i + 1] < 4 ? crow[i + 1] : 4];
      *p++ = static_cast<uint8_t>((hi << 4) | lo);
    }
    if (L & 1) {
      *p++ = static_cast<uint8_t>(kCode2Nib[crow[L - 1] < 4 ? crow[L - 1] : 4]
                                  << 4);
    }
    std::memcpy(p, qrow, static_cast<size_t>(L));
    p += L;
    // RG:Z
    p[0] = 'R'; p[1] = 'G'; p[2] = 'Z';
    std::memcpy(p + 3, rg, static_cast<size_t>(rg_len));
    p += 3 + rg_len;
    *p++ = 0;
    // depth/error aggregates over clamped i16 values
    int32_t max_d = 0, min_d = 0;
    int64_t tot_d = 0, tot_e = 0;
    if (L > 0) {
      max_d = -1;
      min_d = 0x7FFFFFFF;
      for (int32_t i = 0; i < L; ++i) {
        const int32_t d16 = drow[i] < 32767 ? drow[i] : 32767;
        const int32_t e16 = erow[i] < 32767 ? erow[i] : 32767;
        if (d16 > max_d) max_d = d16;
        if (d16 < min_d) min_d = d16;
        tot_d += d16;
        tot_e += e16;
      }
    }
    p[0] = 'c'; p[1] = 'D'; p[2] = 'i';
    put_u32(p + 3, static_cast<uint32_t>(L > 0 ? max_d : 0));
    p += 7;
    p[0] = 'c'; p[1] = 'M'; p[2] = 'i';
    put_u32(p + 3, static_cast<uint32_t>(L > 0 ? min_d : 0));
    p += 7;
    const float rate =
        tot_d ? static_cast<float>(tot_e) / static_cast<float>(tot_d) : 0.0f;
    p[0] = 'c'; p[1] = 'E'; p[2] = 'f';
    uint32_t rate_bits;
    std::memcpy(&rate_bits, &rate, 4);
    put_u32(p + 3, rate_bits);
    p += 7;
    if (per_base_tags) {
      p[0] = 'c'; p[1] = 'd'; p[2] = 'B'; p[3] = 's';
      put_u32(p + 4, static_cast<uint32_t>(L));
      p += 8;
      for (int32_t i = 0; i < L; ++i) {
        const int32_t d16 = drow[i] < 32767 ? drow[i] : 32767;
        put_u16(p, static_cast<uint16_t>(static_cast<int16_t>(d16)));
        p += 2;
      }
      p[0] = 'c'; p[1] = 'e'; p[2] = 'B'; p[3] = 's';
      put_u32(p + 4, static_cast<uint32_t>(L));
      p += 8;
      for (int32_t i = 0; i < L; ++i) {
        const int32_t e16 = erow[i] < 32767 ? erow[i] : 32767;
        put_u16(p, static_cast<uint16_t>(static_cast<int16_t>(e16)));
        p += 2;
      }
    }
    p[0] = 'M'; p[1] = 'I'; p[2] = 'Z';
    std::memcpy(p + 3, mi_p, static_cast<size_t>(mi_len[j]));
    p += 3 + mi_len[j];
    *p++ = 0;
    if (rx_addr[j] != 0) {
      p[0] = 'R'; p[1] = 'X'; p[2] = 'Z';
      std::memcpy(p + 3, reinterpret_cast<const uint8_t*>(rx_addr[j]),
                  static_cast<size_t>(rx_len[j]));
      p += 3 + rx_len[j];
      *p++ = 0;
    }
    const long rec_size = p - rec;
    put_u32(out + off, static_cast<uint32_t>(rec_size));
    off += 4 + rec_size;
    rec_end[j] = off;
  }
  return off;
}

// Serialize J unmapped duplex consensus records. Byte-exact analog of
// DuplexConsensusCaller._build_record (consensus/duplex.py:367-435; reference
// duplex_read_into, duplex_caller.rs:1056-1249): header + name + packed seq +
// quals, then tags MI:Z, RG:Z, aD/aE/aM [+ac/ad/ae/aq], bD/bE/bM
// [+bc/bd/be/bq], cD/cE/cM, [RX:Z]. All per-record data arrives as raw
// addresses; b_present[j] == 0 marks a missing BA strand (bD/bE/bM still
// written as zeros, per-base b tags skipped); rx_addr[j] == 0 marks no RX.
// a_* arrays have a_len[j] entries (full strand length), code/qual/err have
// lens[j] (the combined length). Returns total bytes, or -1 on overflow.
long fgumi_build_duplex_records(
    const int64_t* code_addr, const int64_t* qual_addr, const int64_t* err_addr,
    const int32_t* lens, const int32_t* flags, long J, const uint8_t* prefix,
    int prefix_len, const int64_t* mi_addr, const int32_t* mi_len,
    const int64_t* a_code, const int64_t* a_qual, const int64_t* a_depth,
    const int64_t* a_err, const int32_t* a_len,
    const int64_t* b_code, const int64_t* b_qual, const int64_t* b_depth,
    const int64_t* b_err, const int32_t* b_len, const uint8_t* b_present,
    const int64_t* rx_addr, const int32_t* rx_len, const uint8_t* rg,
    int rg_len, int per_base_tags, uint8_t* out, long out_cap,
    int64_t* rec_end) {
  const uint8_t kBase[5] = {'A', 'C', 'G', 'T', 'N'};
  long off = 0;
  for (long j = 0; j < J; ++j) {
    const int32_t L = lens[j];
    const int32_t aL = a_len[j];
    const int32_t bL = b_present[j] ? b_len[j] : 0;
    const uint8_t* crow = reinterpret_cast<const uint8_t*>(code_addr[j]);
    const uint8_t* qrow = reinterpret_cast<const uint8_t*>(qual_addr[j]);
    const int32_t* erow = reinterpret_cast<const int32_t*>(err_addr[j]);
    const uint8_t* mi_p = reinterpret_cast<const uint8_t*>(mi_addr[j]);
    const int32_t name_len = prefix_len + 1 + mi_len[j];
    if (name_len + 1 > 255) return -2;  // l_read_name is a u8 (caller raises)
    long need = 4 + 32 + name_len + 1 + (L + 1) / 2 + L;
    need += (3 + mi_len[j] + 1) + (3 + rg_len + 1);  // MI RG
    need += 6 * 7 + 3 * 7;  // aD/aM/bD/bM/cD/cM + aE/bE/cE (7 bytes each)
    if (per_base_tags) {
      need += (3 + aL + 1) + 2 * (8 + 2 * static_cast<long>(aL)) + (3 + aL + 1);
      if (b_present[j]) {
        need += (3 + bL + 1) + 2 * (8 + 2 * static_cast<long>(bL))
                + (3 + bL + 1);
      }
    }
    if (rx_addr[j] != 0) need += 3 + rx_len[j] + 1;
    if (off + need > out_cap) return -1;

    uint8_t* rec = out + off + 4;
    put_u32(rec + 0, 0xFFFFFFFFu);
    put_u32(rec + 4, 0xFFFFFFFFu);
    rec[8] = static_cast<uint8_t>(name_len + 1);
    rec[9] = 0;
    put_u16(rec + 10, 4680);
    put_u16(rec + 12, 0);
    put_u16(rec + 14, static_cast<uint16_t>(flags[j]));
    put_u32(rec + 16, static_cast<uint32_t>(L));
    put_u32(rec + 20, 0xFFFFFFFFu);
    put_u32(rec + 24, 0xFFFFFFFFu);
    put_u32(rec + 28, 0);
    uint8_t* p = rec + 32;
    std::memcpy(p, prefix, static_cast<size_t>(prefix_len));
    p += prefix_len;
    *p++ = ':';
    std::memcpy(p, mi_p, static_cast<size_t>(mi_len[j]));
    p += mi_len[j];
    *p++ = 0;
    for (int32_t i = 0; i + 1 < L; i += 2) {
      const uint8_t hi = kCode2Nib[crow[i] < 4 ? crow[i] : 4];
      const uint8_t lo = kCode2Nib[crow[i + 1] < 4 ? crow[i + 1] : 4];
      *p++ = static_cast<uint8_t>((hi << 4) | lo);
    }
    if (L & 1) {
      *p++ = static_cast<uint8_t>(kCode2Nib[crow[L - 1] < 4 ? crow[L - 1] : 4]
                                  << 4);
    }
    std::memcpy(p, qrow, static_cast<size_t>(L));
    p += L;
    p[0] = 'M'; p[1] = 'I'; p[2] = 'Z';
    std::memcpy(p + 3, mi_p, static_cast<size_t>(mi_len[j]));
    p += 3 + mi_len[j];
    *p++ = 0;
    p[0] = 'R'; p[1] = 'G'; p[2] = 'Z';
    std::memcpy(p + 3, rg, static_cast<size_t>(rg_len));
    p += 3 + rg_len;
    *p++ = 0;

    // one strand's aggregate + optional per-base tags (strand_metrics +
    // the ac/ad/ae/aq block, duplex.py:379-407)
    auto strand_tags = [&](char sc, const uint8_t* scode, const uint8_t* squal,
                           const int32_t* sdep, const int32_t* serr,
                           int32_t sl, bool present, bool base_tags) {
      int32_t mx = 0, mn = 0;
      float rate = 0.0f;
      if (sl > 0) {
        mx = -1;
        mn = 0x7FFFFFFF;
        int64_t td = 0, te = 0;
        for (int32_t i = 0; i < sl; ++i) {
          const int32_t d16 = sdep[i] < 32767 ? sdep[i] : 32767;
          const int32_t e16 = serr[i] < 32767 ? serr[i] : 32767;
          if (d16 > mx) mx = d16;
          if (d16 < mn) mn = d16;
          td += d16;
          te += e16;
        }
        rate = td ? static_cast<float>(te) / static_cast<float>(td) : 0.0f;
      }
      p[0] = sc; p[1] = 'D'; p[2] = 'i';
      put_u32(p + 3, static_cast<uint32_t>(sl > 0 ? mx : 0));
      p += 7;
      uint32_t bits;
      std::memcpy(&bits, &rate, 4);
      p[0] = sc; p[1] = 'E'; p[2] = 'f';
      put_u32(p + 3, bits);
      p += 7;
      p[0] = sc; p[1] = 'M'; p[2] = 'i';
      put_u32(p + 3, static_cast<uint32_t>(sl > 0 ? mn : 0));
      p += 7;
      if (base_tags && present) {
        p[0] = sc; p[1] = 'c'; p[2] = 'Z';
        p += 3;
        for (int32_t i = 0; i < sl; ++i) *p++ = kBase[scode[i] < 4 ? scode[i] : 4];
        *p++ = 0;
        p[0] = sc; p[1] = 'd'; p[2] = 'B'; p[3] = 's';
        put_u32(p + 4, static_cast<uint32_t>(sl));
        p += 8;
        for (int32_t i = 0; i < sl; ++i) {
          put_u16(p, static_cast<uint16_t>(
                         static_cast<int16_t>(sdep[i] < 32767 ? sdep[i] : 32767)));
          p += 2;
        }
        p[0] = sc; p[1] = 'e'; p[2] = 'B'; p[3] = 's';
        put_u32(p + 4, static_cast<uint32_t>(sl));
        p += 8;
        for (int32_t i = 0; i < sl; ++i) {
          put_u16(p, static_cast<uint16_t>(
                         static_cast<int16_t>(serr[i] < 32767 ? serr[i] : 32767)));
          p += 2;
        }
        p[0] = sc; p[1] = 'q'; p[2] = 'Z';
        p += 3;
        for (int32_t i = 0; i < sl; ++i) *p++ = static_cast<uint8_t>(squal[i] + 33);
        *p++ = 0;
      }
    };
    strand_tags('a', reinterpret_cast<const uint8_t*>(a_code[j]),
                reinterpret_cast<const uint8_t*>(a_qual[j]),
                reinterpret_cast<const int32_t*>(a_depth[j]),
                reinterpret_cast<const int32_t*>(a_err[j]), aL, true,
                per_base_tags != 0);
    strand_tags('b', reinterpret_cast<const uint8_t*>(b_code[j]),
                reinterpret_cast<const uint8_t*>(b_qual[j]),
                reinterpret_cast<const int32_t*>(b_depth[j]),
                reinterpret_cast<const int32_t*>(b_err[j]), bL,
                b_present[j] != 0, per_base_tags != 0);

    // combined cD/cE/cM: per-strand per-base i16 clamp before summing
    // (duplex.py:409-419, duplex_caller.rs:1188-1215)
    const int32_t* adp = reinterpret_cast<const int32_t*>(a_depth[j]);
    const int32_t* bdp = reinterpret_cast<const int32_t*>(b_depth[j]);
    int64_t comb_max = 0, comb_min = 0, total_d = 0, total_e = 0;
    if (L > 0) {
      comb_max = -1;
      comb_min = 0x7FFFFFFFFFFFLL;
      for (int32_t i = 0; i < L; ++i) {
        int64_t c = adp[i] < 32767 ? adp[i] : 32767;
        if (b_present[j]) c += bdp[i] < 32767 ? bdp[i] : 32767;
        if (c > comb_max) comb_max = c;
        if (c < comb_min) comb_min = c;
        total_d += c;
        total_e += erow[i] < 32767 ? erow[i] : 32767;
      }
    }
    const float crate =
        total_d ? static_cast<float>(total_e) / static_cast<float>(total_d)
                : 0.0f;
    p[0] = 'c'; p[1] = 'D'; p[2] = 'i';
    put_u32(p + 3, static_cast<uint32_t>(L > 0 ? comb_max : 0));
    p += 7;
    uint32_t crate_bits;
    std::memcpy(&crate_bits, &crate, 4);
    p[0] = 'c'; p[1] = 'E'; p[2] = 'f';
    put_u32(p + 3, crate_bits);
    p += 7;
    p[0] = 'c'; p[1] = 'M'; p[2] = 'i';
    put_u32(p + 3, static_cast<uint32_t>(L > 0 ? comb_min : 0));
    p += 7;
    if (rx_addr[j] != 0) {
      p[0] = 'R'; p[1] = 'X'; p[2] = 'Z';
      std::memcpy(p + 3, reinterpret_cast<const uint8_t*>(rx_addr[j]),
                  static_cast<size_t>(rx_len[j]));
      p += 3 + rx_len[j];
      *p++ = 0;
    }
    const long rec_size = p - rec;
    put_u32(out + off, static_cast<uint32_t>(rec_size));
    off += 4 + rec_size;
    rec_end[j] = off;
  }
  return off;
}

// Full case-insensitive IUPAC base -> BAM nibble table (io/bam.py
// BASE_TO_NIBBLE: "=ACMGRSVTWYHKDBN" both cases, everything else 15/N).
static const uint8_t* iupac_nibble_table() {
  static uint8_t t[256];
  static bool init = false;
  if (!init) {
    const char* order = "=ACMGRSVTWYHKDBN";
    for (int i = 0; i < 256; ++i) t[i] = 15;
    for (int i = 0; i < 16; ++i) {
      const char c = order[i];
      t[static_cast<uint8_t>(c)] = static_cast<uint8_t>(i);
      if (c >= 'A' && c <= 'Z')
        t[static_cast<uint8_t>(c - 'A' + 'a')] = static_cast<uint8_t>(i);
    }
    init = true;
  }
  return t;
}

// Serialize J unmapped CODEC consensus records. Byte-exact analog of
// CodecConsensusCaller._build_record (consensus/codec.py; reference
// build_output_record_into, codec_caller.rs:1374-1539): header + name +
// packed seq + quals, then tags RG:Z, [MI:Z], cD/cM/cE, aD/aM/aE, bD/bM/bE,
// [ad/bd/ae/be:B,s ac/bc:Z aq/bq:Z], [RX:Z]. Per-record data arrives as raw
// addresses: seq/qual/strand-base/strand-qual rows are uint8 of length
// lens[j]; cons_err/strand depth+error rows are int64. mi_len[j] < 0 skips
// MI; rx_addr[j] == 0 skips RX. Returns total bytes, -2 on an over-long
// name, -1 on overflow.
long fgumi_build_codec_records(
    const int64_t* seq_addr, const int64_t* qual_addr,
    const int64_t* cons_err_addr,
    const int64_t* a_base, const int64_t* a_qual, const int64_t* a_depth,
    const int64_t* a_err,
    const int64_t* b_base, const int64_t* b_qual, const int64_t* b_depth,
    const int64_t* b_err,
    const int32_t* lens, long J,
    const int64_t* name_addr, const int32_t* name_len,
    const int64_t* mi_addr, const int32_t* mi_len,
    const int64_t* rx_addr, const int32_t* rx_len,
    const uint8_t* rg, int rg_len, int flags, int per_base_tags,
    uint8_t* out, long out_cap, int64_t* rec_end) {
  const uint8_t* nib = iupac_nibble_table();
  long off = 0;
  for (long j = 0; j < J; ++j) {
    const int32_t L = lens[j];
    const int32_t nl = name_len[j];
    if (nl + 1 > 255) return -2;
    long need = 4 + 32 + nl + 1 + (L + 1) / 2 + L;
    need += 3 + rg_len + 1;
    if (mi_len[j] >= 0) need += 3 + mi_len[j] + 1;
    need += 9 * 7;  // cD cM cE aD aM aE bD bM bE
    if (per_base_tags)
      need += 4 * (8 + 2 * static_cast<long>(L)) + 4 * (3 + L + 1);
    if (rx_addr[j] != 0) need += 3 + rx_len[j] + 1;
    if (off + need > out_cap) return -1;

    const uint8_t* seq = reinterpret_cast<const uint8_t*>(seq_addr[j]);
    const uint8_t* qual = reinterpret_cast<const uint8_t*>(qual_addr[j]);
    const int64_t* cerr = reinterpret_cast<const int64_t*>(cons_err_addr[j]);
    uint8_t* rec = out + off + 4;
    put_u32(rec + 0, 0xFFFFFFFFu);
    put_u32(rec + 4, 0xFFFFFFFFu);
    rec[8] = static_cast<uint8_t>(nl + 1);
    rec[9] = 0;
    put_u16(rec + 10, 4680);
    put_u16(rec + 12, 0);
    put_u16(rec + 14, static_cast<uint16_t>(flags));
    put_u32(rec + 16, static_cast<uint32_t>(L));
    put_u32(rec + 20, 0xFFFFFFFFu);
    put_u32(rec + 24, 0xFFFFFFFFu);
    put_u32(rec + 28, 0);
    uint8_t* p = rec + 32;
    std::memcpy(p, reinterpret_cast<const uint8_t*>(name_addr[j]),
                static_cast<size_t>(nl));
    p += nl;
    *p++ = 0;
    for (int32_t i = 0; i + 1 < L; i += 2)
      *p++ = static_cast<uint8_t>((nib[seq[i]] << 4) | nib[seq[i + 1]]);
    if (L & 1) *p++ = static_cast<uint8_t>(nib[seq[L - 1]] << 4);
    std::memcpy(p, qual, static_cast<size_t>(L));
    p += L;
    p[0] = 'R'; p[1] = 'G'; p[2] = 'Z';
    std::memcpy(p + 3, rg, static_cast<size_t>(rg_len));
    p += 3 + rg_len;
    *p++ = 0;
    if (mi_len[j] >= 0) {
      p[0] = 'M'; p[1] = 'I'; p[2] = 'Z';
      std::memcpy(p + 3, reinterpret_cast<const uint8_t*>(mi_addr[j]),
                  static_cast<size_t>(mi_len[j]));
      p += 3 + mi_len[j];
      *p++ = 0;
    }

    const int64_t* adp = reinterpret_cast<const int64_t*>(a_depth[j]);
    const int64_t* aer = reinterpret_cast<const int64_t*>(a_err[j]);
    const int64_t* bdp = reinterpret_cast<const int64_t*>(b_depth[j]);
    const int64_t* ber = reinterpret_cast<const int64_t*>(b_err[j]);
    auto cap16 = [](int64_t v) -> int64_t { return v < 32767 ? v : 32767; };

    // cD/cM over cap(a)+cap(b); cE = sum(cap(cons_err)) / sum(total_depth)
    int64_t td_max = 0, td_min = 0, td_sum = 0, ce_sum = 0;
    if (L > 0) {
      td_max = -1;
      td_min = 0x7FFFFFFFFFFFLL;
      for (int32_t i = 0; i < L; ++i) {
        const int64_t td = cap16(adp[i]) + cap16(bdp[i]);
        if (td > td_max) td_max = td;
        if (td < td_min) td_min = td;
        td_sum += td;
        ce_sum += cap16(cerr[i]);
      }
    }
    const float crate = td_sum
        ? static_cast<float>(ce_sum) / static_cast<float>(td_sum) : 0.0f;
    p[0] = 'c'; p[1] = 'D'; p[2] = 'i';
    put_u32(p + 3, static_cast<uint32_t>(L > 0 ? td_max : 0));
    p += 7;
    p[0] = 'c'; p[1] = 'M'; p[2] = 'i';
    put_u32(p + 3, static_cast<uint32_t>(L > 0 ? td_min : 0));
    p += 7;
    uint32_t bits;
    std::memcpy(&bits, &crate, 4);
    p[0] = 'c'; p[1] = 'E'; p[2] = 'f';
    put_u32(p + 3, bits);
    p += 7;

    // aD/aM/aE then bD/bM/bE (strand aggregates over capped values)
    const int64_t* deps[2] = {adp, bdp};
    const int64_t* errs[2] = {aer, ber};
    const char sc[2] = {'a', 'b'};
    for (int s = 0; s < 2; ++s) {
      int64_t mx = 0, mn = 0, dsum = 0, esum = 0;
      if (L > 0) {
        mx = -1;
        mn = 0x7FFFFFFFFFFFLL;
        for (int32_t i = 0; i < L; ++i) {
          const int64_t d = cap16(deps[s][i]);
          if (d > mx) mx = d;
          if (d < mn) mn = d;
          dsum += d;
          esum += cap16(errs[s][i]);
        }
      }
      const float srate = dsum
          ? static_cast<float>(esum) / static_cast<float>(dsum) : 0.0f;
      p[0] = sc[s]; p[1] = 'D'; p[2] = 'i';
      put_u32(p + 3, static_cast<uint32_t>(L > 0 ? mx : 0));
      p += 7;
      p[0] = sc[s]; p[1] = 'M'; p[2] = 'i';
      put_u32(p + 3, static_cast<uint32_t>(L > 0 ? mn : 0));
      p += 7;
      std::memcpy(&bits, &srate, 4);
      p[0] = sc[s]; p[1] = 'E'; p[2] = 'f';
      put_u32(p + 3, bits);
      p += 7;
    }

    if (per_base_tags) {
      // ad bd ae be (B,s of capped values), then ac bc (Z), aq bq (Z +33)
      const int64_t* rows[4] = {adp, bdp, aer, ber};
      const char tag0[4] = {'a', 'b', 'a', 'b'};
      const char tag1[4] = {'d', 'd', 'e', 'e'};
      for (int t = 0; t < 4; ++t) {
        p[0] = tag0[t]; p[1] = tag1[t]; p[2] = 'B'; p[3] = 's';
        put_u32(p + 4, static_cast<uint32_t>(L));
        p += 8;
        for (int32_t i = 0; i < L; ++i) {
          put_u16(p, static_cast<uint16_t>(
                         static_cast<int16_t>(cap16(rows[t][i]))));
          p += 2;
        }
      }
      const uint8_t* sb[2] = {reinterpret_cast<const uint8_t*>(a_base[j]),
                              reinterpret_cast<const uint8_t*>(b_base[j])};
      const uint8_t* sq[2] = {reinterpret_cast<const uint8_t*>(a_qual[j]),
                              reinterpret_cast<const uint8_t*>(b_qual[j])};
      for (int s = 0; s < 2; ++s) {
        p[0] = sc[s]; p[1] = 'c'; p[2] = 'Z';
        std::memcpy(p + 3, sb[s], static_cast<size_t>(L));
        p += 3 + L;
        *p++ = 0;
      }
      for (int s = 0; s < 2; ++s) {
        p[0] = sc[s]; p[1] = 'q'; p[2] = 'Z';
        p += 3;
        for (int32_t i = 0; i < L; ++i)
          *p++ = static_cast<uint8_t>(sq[s][i] + 33);
        *p++ = 0;
      }
    }
    if (rx_addr[j] != 0) {
      p[0] = 'R'; p[1] = 'X'; p[2] = 'Z';
      std::memcpy(p + 3, reinterpret_cast<const uint8_t*>(rx_addr[j]),
                  static_cast<size_t>(rx_len[j]));
      p += 3 + rx_len[j];
      *p++ = 0;
    }
    const long rec_size = p - rec;
    put_u32(out + off, static_cast<uint32_t>(rec_size));
    off += 4 + rec_size;
    rec_end[j] = off;
  }
  return off;
}

// Per-segment depth/error counts for the ragged consensus layout: codes is
// the dense (N, L) read-row array (N = starts[J]), winner the (J, L) called
// bases; depth[j,i] = valid (non-N) observations, errors[j,i] = valid
// observations disagreeing with the winner (all of them when the winner is
// N). Integer-exact replacement for the numpy reduceat path in
// ops/kernel.py::_finish_segments (reference _call_epilogue obs arithmetic).
void fgumi_segment_depth_errors(const uint8_t* codes, const uint8_t* winner,
                                const int64_t* starts, long J, long L,
                                int32_t* depth, int32_t* errors) {
  for (long j = 0; j < J; ++j) {
    int32_t* drow = depth + j * L;
    int32_t* erow = errors + j * L;
    const uint8_t* wrow = winner + j * L;
    std::memset(drow, 0, static_cast<size_t>(L) * 4);
    std::memset(erow, 0, static_cast<size_t>(L) * 4);
    for (int64_t r = starts[j]; r < starts[j + 1]; ++r) {
      const uint8_t* crow = codes + r * L;
      for (long i = 0; i < L; ++i) {
        const uint8_t c = crow[i];
        if (c != 4) {
          ++drow[i];
          erow[i] += (c != wrow[i]);
        }
      }
    }
  }
}

// fgumi_segment_depth_errors with explicit, possibly non-contiguous row
// ranges [lo[j], hi[j]) per segment (the duplex exact-error pass sums a
// molecule's two strand segs, which are not adjacent in the dense layout).
void fgumi_segment_depth_errors_ranges(const uint8_t* codes,
                                       const uint8_t* winner,
                                       const int64_t* lo, const int64_t* hi,
                                       long J, long L, int32_t* depth,
                                       int32_t* errors) {
  for (long j = 0; j < J; ++j) {
    int32_t* drow = depth + j * L;
    int32_t* erow = errors + j * L;
    const uint8_t* wrow = winner + j * L;
    std::memset(drow, 0, static_cast<size_t>(L) * 4);
    std::memset(erow, 0, static_cast<size_t>(L) * 4);
    for (int64_t r = lo[j]; r < hi[j]; ++r) {
      const uint8_t* crow = codes + r * L;
      for (long i = 0; i < L; ++i) {
        const uint8_t c = crow[i];
        if (c != 4) {
          ++drow[i];
          erow[i] += (c != wrow[i]);
        }
      }
    }
  }
}

namespace {

inline void put_u32_be(uint8_t* p, uint32_t v) {
  p[0] = v >> 24;
  p[1] = (v >> 16) & 0xFF;
  p[2] = (v >> 8) & 0xFF;
  p[3] = v & 0xFF;
}

inline void put_u64_be(uint8_t* p, uint64_t v) {
  for (int i = 0; i < 8; ++i) p[i] = (v >> (56 - 8 * i)) & 0xFF;
}

// defined below (overlap section)
bool parse_mc_cigar(const uint8_t* s, int64_t len, int64_t* leading_soft,
                    int64_t* ref_len, int64_t* trailing_soft);
// defined below (tag scan section)
inline int64_t tag_fixed_size(uint8_t typ);

}  // namespace

// Batch template-coordinate sort keys (sort/keys.py::
// template_coordinate_key_bytes; reference fgumi-sort/src/inline.rs
// TemplateKey). Writes each record's packed key at out + out_off[i]
// (28 + name_len bytes: 16B ends, 2B strand, 2B library, 8B MI value,
// 1B MI sub, name, NUL, is_upper). Returns 0.
long fgumi_template_coord_keys(
    const uint8_t* buf, const int64_t* data_off, const int32_t* l_read_name,
    const int64_t* cigar_off, const int32_t* n_cigar, const int32_t* flag,
    const int32_t* ref_id, const int32_t* pos, const int32_t* next_ref_id,
    const int32_t* next_pos, const int64_t* mc_off, const int32_t* mc_len,
    const int64_t* mi_off, const int32_t* mi_len, const int32_t* lib_ord,
    long n, uint8_t* out, const int64_t* out_off) {
  const int64_t kTidUnmapped = 1LL << 31;
  const int64_t kPosSentinel = 0x7FFFFFFFLL;
  const uint32_t kPosBias = 0x40000000u;
  for (long i = 0; i < n; ++i) {
    const int32_t f = flag[i];
    // own end (keys.py::_own_end): unclipped 5' position, 1-based
    int64_t own_tid, own_pos;
    bool own_rev = false;
    if (f & 0x4) {
      own_tid = kTidUnmapped;
      own_pos = kPosSentinel;
    } else {
      own_tid = ref_id[i];
      own_rev = (f & 0x10) != 0;
      const uint8_t* cp = buf + cigar_off[i];
      const int32_t nc = n_cigar[i];
      int64_t lead = 0, trail = 0, rlen = 0;
      for (int32_t k = 0; k < nc; ++k) {
        uint32_t v;
        std::memcpy(&v, cp + 4 * k, 4);
        const uint32_t op = v & 0xF;
        const int64_t ln = v >> 4;
        if (op == 0 || op == 2 || op == 3 || op == 7 || op == 8) rlen += ln;
      }
      for (int32_t k = 0; k < nc; ++k) {
        uint32_t v;
        std::memcpy(&v, cp + 4 * k, 4);
        const uint32_t op = v & 0xF;
        if (op == 4 || op == 5) lead += v >> 4; else break;
      }
      for (int32_t k = nc - 1; k >= 0; --k) {
        uint32_t v;
        std::memcpy(&v, cp + 4 * k, 4);
        const uint32_t op = v & 0xF;
        if (op == 4 || op == 5) trail += v >> 4; else break;
      }
      const int64_t un_start = pos[i] - lead;
      const int64_t un_end = pos[i] + rlen - 1 + trail;
      own_pos = (own_rev ? un_end : un_start) + 1;
    }
    // mate end (keys.py::_mate_end) via the MC tag
    int64_t mate_tid, mate_pos;
    bool mate_rev = false;
    if (!(f & 0x1) || (f & 0x8) || next_ref_id[i] < 0) {
      mate_tid = kTidUnmapped;
      mate_pos = kPosSentinel;
    } else {
      mate_tid = next_ref_id[i];
      mate_rev = (f & 0x20) != 0;
      int64_t lead = 0, rlen = 0, trail = 0;
      if (mc_off[i] >= 0) {
        int64_t l2, r2, t2;
        if (parse_mc_cigar(buf + mc_off[i], mc_len[i], &l2, &r2, &t2)) {
          lead = l2;
          rlen = r2;
          trail = t2;
        }
      }
      const int64_t mp1 = next_pos[i] + 1;
      mate_pos = mate_rev ? (mp1 - 1 + (rlen > 1 ? rlen : 1) - 1 + trail + 1)
                          : (mp1 - lead);
    }
    // tuple compare (tid, pos, rev): lower end first
    bool own_low =
        (own_tid != mate_tid) ? (own_tid < mate_tid)
        : (own_pos != mate_pos) ? (own_pos < mate_pos)
                                : (own_rev <= mate_rev);
    int64_t tid1, tid2, pos1, pos2;
    bool neg1, neg2;
    uint8_t is_upper;
    if (own_low) {
      tid1 = own_tid; pos1 = own_pos; neg1 = own_rev;
      tid2 = mate_tid; pos2 = mate_pos; neg2 = mate_rev;
      is_upper = 0;
    } else {
      tid1 = mate_tid; pos1 = mate_pos; neg1 = mate_rev;
      tid2 = own_tid; pos2 = own_pos; neg2 = own_rev;
      is_upper = 1;
    }
    // MI value (external.py::_mi_key): int() of the prefix before '/'
    // (optional surrounding ASCII whitespace and sign; negatives clamp to
    // 0), suffix 'A' -> 0, anything else (incl. no suffix) -> 1; absent or
    // non-string tag -> (0, 0)
    uint64_t mi_val = 0;
    uint8_t mi_sub = 0;
    if (mi_off[i] >= 0) {
      const uint8_t* mp = buf + mi_off[i];
      const int32_t ml = mi_len[i];
      int32_t slash = 0;
      while (slash < ml && mp[slash] != '/') ++slash;
      int32_t b0 = 0, b1 = slash;  // int() strips whitespace both ends
      while (b0 < b1 && (mp[b0] == ' ' || (mp[b0] >= '\t' && mp[b0] <= '\r')))
        ++b0;
      while (b1 > b0 && (mp[b1 - 1] == ' '
                         || (mp[b1 - 1] >= '\t' && mp[b1 - 1] <= '\r')))
        --b1;
      bool negative = false;
      if (b0 < b1 && (mp[b0] == '+' || mp[b0] == '-')) {
        negative = mp[b0] == '-';
        ++b0;
      }
      bool digits_ok = b0 < b1;
      uint64_t v = 0;
      const uint64_t kU64Max = ~0ULL;
      for (int32_t k = b0; k < b1; ++k) {
        if (mp[k] < '0' || mp[k] > '9') {
          digits_ok = false;
          break;
        }
        if (v > (kU64Max - (mp[k] - '0')) / 10) {
          v = kU64Max;  // saturate like the Python min(value, u64::MAX)
        } else {
          v = v * 10 + (mp[k] - '0');
        }
      }
      mi_val = (digits_ok && !negative) ? v : 0;  // max(0, ...) clamps sign
      mi_sub = (slash + 2 == ml && mp[slash + 1] == 'A') ? 0 : 1;
    }
    uint8_t* p = out + out_off[i];
    put_u32_be(p + 0, static_cast<uint32_t>(tid1));
    put_u32_be(p + 4, static_cast<uint32_t>(tid2));
    put_u32_be(p + 8, static_cast<uint32_t>(pos1) + kPosBias);
    put_u32_be(p + 12, static_cast<uint32_t>(pos2) + kPosBias);
    p[16] = neg1 ? 0 : 1;
    p[17] = neg2 ? 0 : 1;
    p[18] = (lib_ord[i] >> 8) & 0xFF;
    p[19] = lib_ord[i] & 0xFF;
    put_u64_be(p + 20, mi_val);
    p[28] = mi_sub;
    const int32_t nl = l_read_name[i] - 1;
    std::memcpy(p + 29, buf + data_off[i] + 32, static_cast<size_t>(nl));
    p[29 + nl] = 0;
    p[30 + nl] = is_upper;
  }
  return 0;
}

// Batch unclipped 5' positions (core/template.py::unclipped_5prime):
// forward reads -> unclipped start (pos - leading S/H), reverse -> unclipped
// end (pos + ref_len - 1 + trailing S/H). Unmapped records get pos as-is
// (callers sentinel them by flag).
void fgumi_unclipped_5prime(const uint8_t* buf, const int64_t* cigar_off,
                            const int32_t* n_cigar, const int32_t* flag,
                            const int32_t* pos, long n, int64_t* out) {
  for (long i = 0; i < n; ++i) {
    const uint8_t* cp = buf + cigar_off[i];
    const int32_t nc = n_cigar[i];
    if (flag[i] & 0x10) {
      int64_t rlen = 0, trail = 0;
      for (int32_t k = 0; k < nc; ++k) {
        uint32_t v;
        std::memcpy(&v, cp + 4 * k, 4);
        const uint32_t op = v & 0xF;
        if (op == 0 || op == 2 || op == 3 || op == 7 || op == 8)
          rlen += v >> 4;
      }
      for (int32_t k = nc - 1; k >= 0; --k) {
        uint32_t v;
        std::memcpy(&v, cp + 4 * k, 4);
        const uint32_t op = v & 0xF;
        if (op == 4 || op == 5) trail += v >> 4; else break;
      }
      out[i] = pos[i] + rlen - 1 + trail;
    } else {
      int64_t lead = 0;
      for (int32_t k = 0; k < nc; ++k) {
        uint32_t v;
        std::memcpy(&v, cp + 4 * k, 4);
        const uint32_t op = v & 0xF;
        if (op == 4 || op == 5) lead += v >> 4; else break;
      }
      out[i] = pos[i] - lead;
    }
  }
}

// Rewrite records with one tag replaced: every existing occurrence of `tag`
// is removed from the aux region (any type; RawRecord.data_without_tag
// semantics) and a fresh Z-typed value appended, each record emitted as
// block_size-prefixed wire bytes, packed contiguously into `out` (sized for
// the worst case sum(data_len + 8 + val_len)). Returns total bytes written,
// or -1 - i on a malformed record's aux region (caller reroutes through the
// Python editor).
long fgumi_rewrite_tag_records(
    const uint8_t* buf, const int64_t* data_off, const int64_t* data_end,
    const int64_t* aux_off, long n, uint8_t t1, uint8_t t2,
    const uint8_t* val_blob, const int64_t* val_off, const int32_t* val_len,
    const int32_t* new_flag, uint8_t* out) {
  int64_t total = 0;
  for (long i = 0; i < n; ++i) {
    uint8_t* dst = out + total + 4;
    const uint8_t* src = buf + data_off[i];
    const int64_t aux0 = aux_off[i] - data_off[i];
    const int64_t dlen = data_end[i] - data_off[i];
    // fixed header + name/cigar/seq/qual copied verbatim
    std::memcpy(dst, src, static_cast<size_t>(aux0));
    int64_t w = aux0;
    int64_t off = aux0;
    bool ok = true;
    while (off + 3 <= dlen) {
      const uint8_t a = src[off];
      const uint8_t b = src[off + 1];
      const uint8_t typ = src[off + 2];
      int64_t size = tag_fixed_size(typ);
      if (size == 0) {
        if (typ == 'Z' || typ == 'H') {
          const uint8_t* nul = static_cast<const uint8_t*>(
              std::memchr(src + off + 3, 0, static_cast<size_t>(dlen - off - 3)));
          if (nul == nullptr) { ok = false; break; }
          size = (nul - (src + off + 3)) + 1;
        } else if (typ == 'B') {
          if (off + 8 > dlen) { ok = false; break; }
          const int64_t esize = tag_fixed_size(src[off + 3]);
          if (esize == 0) { ok = false; break; }
          size = 5 + esize * static_cast<int64_t>(read_u32(src + off + 4));
        } else {
          ok = false;
          break;
        }
      }
      if (off + 3 + size > dlen) { ok = false; break; }
      if (!(a == t1 && b == t2)) {
        std::memcpy(dst + w, src + off, static_cast<size_t>(3 + size));
        w += 3 + size;
      }
      off += 3 + size;
    }
    if (!ok || off != dlen) return -1 - i;
    dst[w] = t1;
    dst[w + 1] = t2;
    dst[w + 2] = 'Z';
    std::memcpy(dst + w + 3, val_blob + val_off[i],
                static_cast<size_t>(val_len[i]));
    w += 3 + val_len[i];
    dst[w++] = 0;
    if (new_flag != nullptr && new_flag[i] >= 0) {
      put_u16(dst + 14, static_cast<uint16_t>(new_flag[i]));
    }
    put_u32(out + total, static_cast<uint32_t>(w));
    total += 4 + w;
  }
  return total;
}

// Picard SUM_OF_BASE_QUALITIES per read (dedup.rs:246-290): sum of qualities
// >= min_q, capped at `cap` per read.
void fgumi_qual_scores(const uint8_t* buf, const int64_t* qual_off,
                       const int32_t* l_seq, long n, int min_q, long cap,
                       int32_t* out) {
  for (long i = 0; i < n; ++i) {
    const uint8_t* q = buf + qual_off[i];
    int64_t s = 0;
    for (int32_t k = 0; k < l_seq[i]; ++k) {
      if (q[k] >= min_q) s += q[k];
    }
    out[i] = static_cast<int32_t>(s < cap ? s : cap);
  }
}

// Per-range UMI scan: has_n = contains 'N'/'n', bases = byte length minus
// '-' separators (group.py::_umi_base_count), ascii = no high-bit bytes
// (non-ASCII UMIs route through the Python path: their decoded character
// count can differ from the byte count). off < 0 -> (-1 bases, 0, 1).
void fgumi_umi_scan(const uint8_t* buf, const int64_t* off,
                    const int32_t* len, long n, uint8_t* has_n,
                    int32_t* bases, uint8_t* ascii) {
  for (long i = 0; i < n; ++i) {
    if (off[i] < 0) {
      has_n[i] = 0;
      bases[i] = -1;
      ascii[i] = 1;
      continue;
    }
    const uint8_t* p = buf + off[i];
    uint8_t nn = 0, asc = 1;
    int32_t dashes = 0;
    for (int32_t k = 0; k < len[i]; ++k) {
      const uint8_t c = p[k];
      nn |= (c == 'N') | (c == 'n');
      asc &= c < 0x80;
      dashes += c == '-';
    }
    has_n[i] = nn;
    bases[i] = len[i] - dashes;
    ascii[i] = asc;
  }
}

// Batch natural-queryname sort keys (sort/keys.py::queryname_key_bytes):
// digit runs as 0x01 + count + stripped digits, text runs as 0x02 + text +
// 0x00, then NUL + 4-byte rank (secondary flag, R1/R2, flag BE). Writes at
// out + out_off[i]; out_len[i] receives the actual key length (the caller
// sizes out_off for the worst case 2 + 2*name_len + 5).
long fgumi_natural_name_keys(const uint8_t* buf, const int64_t* data_off,
                             const int32_t* l_read_name, const int32_t* flag,
                             long n, uint8_t* out, const int64_t* out_off,
                             int32_t* out_len) {
  for (long i = 0; i < n; ++i) {
    const uint8_t* name = buf + data_off[i] + 32;
    const int32_t nl = l_read_name[i] - 1;
    uint8_t* p = out + out_off[i];
    uint8_t* q = p;
    int32_t k = 0;
    while (k < nl) {
      if (name[k] >= '0' && name[k] <= '9') {
        int32_t s = k;
        while (k < nl && name[k] >= '0' && name[k] <= '9') ++k;
        while (s < k && name[s] == '0') ++s;  // lstrip('0'): "000" -> ""
        const int32_t sig = k - s;
        *q++ = 0x01;
        *q++ = static_cast<uint8_t>(sig);
        std::memcpy(q, name + s, static_cast<size_t>(sig));
        q += sig;
      } else {
        *q++ = 0x02;
        while (k < nl && (name[k] < '0' || name[k] > '9')) *q++ = name[k++];
        *q++ = 0x00;
      }
    }
    *q++ = 0x00;
    const int32_t f = flag[i];
    *q++ = (f & 0x900) ? 1 : 0;
    *q++ = !(f & 0x1) ? 0 : ((f & 0x40) ? 1 : 2);
    *q++ = (f >> 8) & 0xFF;
    *q++ = f & 0xFF;
    out_len[i] = static_cast<int32_t>(q - p);
  }
  return 0;
}

// Gather B:s/B:S per-base tag arrays into a dense (n, L) uint16 matrix,
// zero-padded/truncated to L (consensus/filter.py::_per_base_padded
// semantics). val_off points at the B-tag value (subtype byte); -1 or a
// non-16-bit subtype yields count -1 (caller falls back / treats absent).
void fgumi_gather_u16_arrays(const uint8_t* buf, const int64_t* val_off,
                             long n, long L, uint16_t* out,
                             int32_t* out_count) {
  std::memset(out, 0, static_cast<size_t>(n) * L * 2);
  for (long i = 0; i < n; ++i) {
    if (val_off[i] < 0) {
      out_count[i] = -1;
      continue;
    }
    const uint8_t* p = buf + val_off[i];
    const uint8_t sub = p[0];
    if (sub != 's' && sub != 'S') {
      out_count[i] = -2;  // unexpected subtype: caller reroutes
      continue;
    }
    const uint32_t count = read_u32(p + 1);
    const long take = static_cast<long>(count) < L ? count : L;
    uint16_t* row = out + i * L;
    for (long k = 0; k < take; ++k) {
      row[k] = static_cast<uint16_t>(p[5 + 2 * k] | (p[6 + 2 * k] << 8));
    }
    out_count[i] = static_cast<int32_t>(count);
  }
}

// Apply per-record base masks in place: masked positions become N (nibble
// 15) with quality 2. mask is a dense (n, L) uint8 matrix over each
// record's first l_seq positions. skip_existing_n=1 skips already-N
// positions entirely (duplex semantics: no re-mask, quals untouched);
// 0 re-writes quals on already-N positions too (simplex mask_bases).
// newly[i] = newly-masked (previously non-N) count; n_after[i] = total N
// count post-mask (the no-call check input).
void fgumi_apply_masks(uint8_t* buf, const int64_t* seq_off,
                       const int64_t* qual_off, const int32_t* l_seq, long n,
                       const uint8_t* mask, long L, int skip_existing_n,
                       int32_t* newly, int32_t* n_after) {
  for (long i = 0; i < n; ++i) {
    uint8_t* seq = buf + seq_off[i];
    uint8_t* quals = buf + qual_off[i];
    const uint8_t* mrow = mask + i * L;
    const int32_t len = l_seq[i];
    int32_t fresh = 0, total_n = 0;
    for (int32_t k = 0; k < len; ++k) {
      const int shift = (k & 1) ? 0 : 4;
      uint8_t nib = (seq[k >> 1] >> shift) & 0xF;
      const bool was_n = nib == 15;
      if (mrow[k] && !(skip_existing_n && was_n)) {
        if (!was_n) ++fresh;
        seq[k >> 1] = static_cast<uint8_t>(
            (seq[k >> 1] & (0xF << ((k & 1) ? 4 : 0))) | (15u << shift));
        quals[k] = 2;
        nib = 15;
      }
      total_n += nib == 15;
    }
    newly[i] = fresh;
    n_after[i] = total_n;
  }
}

// Batch byte-range equality within one buffer: out[i] = 1 iff both ranges
// are present (offset >= 0), equal length, and byte-identical. Used for
// read-name pair checks without per-record Python slicing.
void fgumi_ranges_equal(const uint8_t* buf, const int64_t* off_a,
                        const int32_t* len_a, const int64_t* off_b,
                        const int32_t* len_b, long n, uint8_t* out) {
  for (long i = 0; i < n; ++i) {
    out[i] = (off_a[i] >= 0 && off_b[i] >= 0 && len_a[i] == len_b[i] &&
              std::memcmp(buf + off_a[i], buf + off_b[i],
                          static_cast<size_t>(len_a[i])) == 0)
                 ? 1
                 : 0;
  }
}

// FNV-1a 64-bit hash per byte range (off < 0 hashes to 0); for duplicate
// detection over read names without materializing Python bytes.
void fgumi_hash_ranges(const uint8_t* buf, const int64_t* off,
                       const int32_t* len, long n, uint64_t* out) {
  for (long i = 0; i < n; ++i) {
    if (off[i] < 0) {
      out[i] = 0;
      continue;
    }
    uint64_t h = 1469598103934665603ULL;
    const uint8_t* p = buf + off[i];
    for (int32_t k = 0; k < len[i]; ++k) {
      h = (h ^ p[k]) * 1099511628211ULL;
    }
    out[i] = h;
  }
}

// Per-segment RX-tag unanimity (consensus/simple_umi.py::consensus_umis fast
// cases). Rows [starts[j], starts[j+1]) with (off, len) per row (off < 0 =
// tag absent). Per segment:
//   out_off[j] = -1  when no row has the tag (emit no RX)
//   out_off[j] = -2  when present values differ, or are unanimous but a
//                    multi-row value needs uppercasing (acgtn present) —
//                    caller runs the Python consensus for these
//   otherwise        out_off/out_len reference the verbatim unanimous value
//                    (single present row, or multi-row already-uppercase)
void fgumi_rx_unanimous(const uint8_t* buf, const int64_t* off,
                        const int32_t* len, const int64_t* starts, long J,
                        int64_t* out_off, int32_t* out_len) {
  for (long j = 0; j < J; ++j) {
    int64_t first = -1;
    int32_t flen = 0;
    long present = 0;
    bool equal = true;
    for (int64_t r = starts[j]; r < starts[j + 1]; ++r) {
      if (off[r] < 0) continue;
      if (present == 0) {
        first = off[r];
        flen = len[r];
      } else if (len[r] != flen ||
                 std::memcmp(buf + off[r], buf + first,
                             static_cast<size_t>(flen)) != 0) {
        equal = false;
        break;
      }
      ++present;
    }
    if (present == 0) {
      out_off[j] = -1;
      out_len[j] = 0;
      continue;
    }
    if (!equal) {
      out_off[j] = -2;
      out_len[j] = 0;
      continue;
    }
    if (present > 1) {
      // multi-read unanimous output is uppercased for a/c/g/t/n only
      bool lower = false;
      const uint8_t* p = buf + first;
      for (int32_t k = 0; k < flen; ++k) {
        const uint8_t c = p[k];
        if (c == 'a' || c == 'c' || c == 'g' || c == 't' || c == 'n') {
          lower = true;
          break;
        }
      }
      if (lower) {
        out_off[j] = -2;
        out_len[j] = 0;
        continue;
      }
    }
    out_off[j] = first;
    out_len[j] = flen;
  }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Batch record decode / pack layer.
//
// C++ equivalents of the reference's raw-record hot path
// (crates/fgumi-raw-bam/src/fields.rs:1-43, raw_bam_record.rs:6-13): Python
// touches per-*batch* numpy arrays, never per-record objects. All offsets are
// into one decompressed chunk buffer; fixed BAM field layout per SAM spec §4.2.
// ---------------------------------------------------------------------------

namespace {

inline int32_t read_i32(const uint8_t* p) {
  return static_cast<int32_t>(read_u32(p));
}

// BAM nibble -> consensus base code (A,C,G,T -> 0..3, everything else 4/N),
// composing NIBBLE_TO_BASE ("=ACMGRSVTWYHKDBN") with BASE_TO_CODE
// (fgumi_tpu/constants.py; reference BASE_TO_INDEX base_builder.rs:307-318).
const uint8_t kNib2Code[16] = {4, 0, 1, 4, 2, 4, 4, 4, 3, 4, 4, 4, 4, 4, 4, 4};

// CIGAR op index (MIDNSHP=X) predicates.
inline bool op_consumes_query(uint32_t op) {
  // M I S = X
  return op == 0 || op == 1 || op == 4 || op == 7 || op == 8;
}
inline bool op_consumes_ref(uint32_t op) {
  // M D N = X
  return op == 0 || op == 2 || op == 3 || op == 7 || op == 8;
}
inline bool op_is_align(uint32_t op) {  // M = X
  return op == 0 || op == 7 || op == 8;
}

struct CigarView {
  const uint8_t* p;
  int32_t n;
  inline uint32_t op(int32_t i) const { return read_u32(p + 4 * i) & 0xF; }
  inline int64_t len(int32_t i) const { return read_u32(p + 4 * i) >> 4; }
};

int64_t cigar_ref_len(const CigarView& c) {
  int64_t total = 0;
  for (int32_t i = 0; i < c.n; ++i) {
    if (op_consumes_ref(c.op(i))) total += c.len(i);
  }
  return total;
}

int64_t cigar_read_len(const CigarView& c) {
  int64_t total = 0;
  for (int32_t i = 0; i < c.n; ++i) {
    if (op_consumes_query(c.op(i))) total += c.len(i);
  }
  return total;
}

int64_t cigar_leading_soft(const CigarView& c) {
  int64_t total = 0;
  for (int32_t i = 0; i < c.n; ++i) {
    const uint32_t op = c.op(i);
    if (op == 4) {       // S
      total += c.len(i);
    } else if (op == 5) {  // H
      continue;
    } else {
      break;
    }
  }
  return total;
}

int64_t cigar_trailing_soft(const CigarView& c) {
  int64_t total = 0;
  for (int32_t i = c.n - 1; i >= 0; --i) {
    const uint32_t op = c.op(i);
    if (op == 4) {
      total += c.len(i);
    } else if (op == 5) {
      continue;
    } else {
      break;
    }
  }
  return total;
}

// 1-based read position at reference position `target`; 0 if in a
// deletion/outside. Mirrors fgumi_tpu/core/overlap.py::_read_pos_at_ref
// (reference overlap.rs:362-411).
int64_t read_pos_at_ref(const CigarView& c, int64_t start_1based,
                        int64_t target, bool before) {
  int64_t ref_pos = start_1based;
  int64_t read_pos = 0;
  for (int32_t i = 0; i < c.n; ++i) {
    const uint32_t op = c.op(i);
    const int64_t length = c.len(i);
    if (op_is_align(op)) {
      if (target < ref_pos) return 0;
      if (target < ref_pos + length) {
        read_pos += target - ref_pos + 1;
        if (before) {
          const int64_t b = read_pos - 1;
          return b > 0 ? b : 0;
        }
        return read_pos;
      }
      read_pos += length;
      ref_pos += length;
    } else if (op == 1 || op == 4) {  // I S
      read_pos += length;
    } else if (op == 2 || op == 3) {  // D N
      if (ref_pos <= target && target < ref_pos + length) return 0;
      ref_pos += length;
    }
  }
  return 0;
}

// Parse an MC-tag CIGAR string: (leading_soft, ref_len, trailing_soft).
// Mirrors overlap.py::parse_soft_clips_and_ref_len (overlap.rs:277-345).
bool parse_mc_cigar(const uint8_t* s, int64_t len, int64_t* leading_soft,
                    int64_t* ref_len, int64_t* trailing_soft) {
  std::vector<std::pair<int64_t, char>> tokens;
  int64_t num = 0;
  bool have_digits = false;
  for (int64_t i = 0; i < len; ++i) {
    const char ch = static_cast<char>(s[i]);
    if (ch >= '0' && ch <= '9') {
      num = num * 10 + (ch - '0');
      have_digits = true;
      continue;
    }
    if (!have_digits || num == 0 ||
        std::strchr("MIDNSHP=X", ch) == nullptr) {
      return false;
    }
    tokens.emplace_back(num, ch);
    num = 0;
    have_digits = false;
  }
  if (have_digits || tokens.empty()) return false;

  const size_t last = tokens.size() - 1;
  int64_t lead = 0, trail = 0, rlen = 0;
  bool saw_ref_op = false;
  for (size_t i = 0; i < tokens.size(); ++i) {
    const int64_t length = tokens[i].first;
    const char op = tokens[i].second;
    if (op == 'M' || op == 'D' || op == 'N' || op == '=' || op == 'X') {
      rlen += length;
      saw_ref_op = true;
    } else if (op == 'I' || op == 'P') {
      // no-op
    } else if (op == 'S') {
      bool leading = true;
      for (size_t j = 0; j < i; ++j) {
        if (tokens[j].second != 'H') { leading = false; break; }
      }
      bool trailing = true;
      for (size_t j = i + 1; j < tokens.size(); ++j) {
        if (tokens[j].second != 'H') { trailing = false; break; }
      }
      if (!leading && !trailing) return false;
      if (saw_ref_op) {
        trail += length;
      } else {
        lead += length;
      }
    } else if (op == 'H') {
      if (i != 0 && i != last) return false;
    } else {
      return false;
    }
  }
  if (!saw_ref_op) return false;
  *leading_soft = lead;
  *ref_len = rlen;
  *trailing_soft = trail;
  return true;
}

// BAM flag bits.
constexpr int32_t kFlagPaired = 0x1;
constexpr int32_t kFlagUnmapped = 0x4;
constexpr int32_t kFlagMateUnmapped = 0x8;
constexpr int32_t kFlagReverse = 0x10;
constexpr int32_t kFlagMateReverse = 0x20;

// Mirrors overlap.py::is_fr_pair (overlap.rs:14-61).
bool is_fr_pair(int32_t flag, int32_t ref_id, int32_t next_ref_id, int32_t pos,
                int32_t next_pos, int32_t tlen, const CigarView& c) {
  if (!(flag & kFlagPaired)) return false;
  if (flag & (kFlagUnmapped | kFlagMateUnmapped)) return false;
  if (ref_id != next_ref_id) return false;
  const bool is_rev = flag & kFlagReverse;
  if (is_rev == static_cast<bool>(flag & kFlagMateReverse)) return false;
  const int64_t start = static_cast<int64_t>(pos) + 1;
  const int64_t mate_start = static_cast<int64_t>(next_pos) + 1;
  int64_t positive_5p, negative_5p;
  if (is_rev) {
    const int64_t rl = cigar_ref_len(c);
    positive_5p = mate_start;
    negative_5p = start + (rl - 1 > 0 ? rl - 1 : 0);
  } else {
    positive_5p = start;
    negative_5p = start + tlen;
  }
  return positive_5p < negative_5p;
}

// Mirrors overlap.py::_bases_extending_past_mate (overlap.rs:172-231).
int64_t bases_extending_past_mate(const CigarView& c, int32_t flag, int32_t pos,
                                  int64_t mate_unclipped_start,
                                  int64_t mate_unclipped_end) {
  const int64_t read_length = cigar_read_len(c);
  const int64_t this_pos = static_cast<int64_t>(pos) + 1;
  if (flag & kFlagReverse) {
    if (this_pos <= mate_unclipped_start) {
      return read_pos_at_ref(c, this_pos, mate_unclipped_start, true);
    }
    const int64_t gap = this_pos - mate_unclipped_start;
    const int64_t v = cigar_leading_soft(c) - (gap > 0 ? gap : 0);
    return v > 0 ? v : 0;
  }
  const int64_t alignment_end = this_pos - 1 + cigar_ref_len(c);
  if (alignment_end >= mate_unclipped_end) {
    const int64_t bases_past =
        read_pos_at_ref(c, this_pos, mate_unclipped_end, false);
    const int64_t v = read_length - bases_past;
    return v > 0 ? v : 0;
  }
  const int64_t gap = mate_unclipped_end - alignment_end;
  const int64_t v = cigar_trailing_soft(c) - (gap > 0 ? gap : 0);
  return v > 0 ? v : 0;
}

// Size of a fixed-width aux value type, or 0 when variable/unknown.
inline int64_t tag_fixed_size(uint8_t typ) {
  switch (typ) {
    case 'A': case 'c': case 'C': return 1;
    case 's': case 'S': return 2;
    case 'i': case 'I': case 'f': return 4;
    default: return 0;
  }
}

}  // namespace

extern "C" {

// Decode fixed-offset fields for n records into struct-of-arrays outputs.
// rec_off[i] points at record i's 4-byte block_size prefix.
void fgumi_decode_fields(const uint8_t* buf, const int64_t* rec_off, long n,
                         int32_t* ref_id, int32_t* pos, int32_t* mapq,
                         int32_t* flag, int32_t* l_seq, int32_t* n_cigar,
                         int32_t* l_read_name, int32_t* next_ref_id,
                         int32_t* next_pos, int32_t* tlen, int64_t* data_off,
                         int64_t* data_end) {
  for (long i = 0; i < n; ++i) {
    const uint8_t* r = buf + rec_off[i];
    const uint32_t block_size = read_u32(r);
    const uint8_t* d = r + 4;
    ref_id[i] = read_i32(d);
    pos[i] = read_i32(d + 4);
    l_read_name[i] = d[8];
    mapq[i] = d[9];
    n_cigar[i] = read_u16(d + 12);
    flag[i] = read_u16(d + 14);
    l_seq[i] = read_i32(d + 16);
    next_ref_id[i] = read_i32(d + 20);
    next_pos[i] = read_i32(d + 24);
    tlen[i] = read_i32(d + 28);
    data_off[i] = rec_off[i] + 4;
    data_end[i] = rec_off[i] + 4 + block_size;
  }
}

// Scan each record's aux TLV region for k 2-byte tags (tags = k*2 bytes).
// Outputs are row-major (n, k): val_off = byte offset of the value (-1 when
// missing), val_len = value length in bytes (Z/H: strlen excluding NUL),
// val_type = type char. A malformed TLV entry stops that record's scan
// (already-found tags are kept). Mirrors io/bam.py::_iter_tags (tags.rs:8-40).
void fgumi_scan_tags(const uint8_t* buf, const int64_t* aux_off,
                     const int64_t* aux_end, long n, const uint8_t* tags,
                     long k, int64_t* val_off, int32_t* val_len,
                     uint8_t* val_type) {
  for (long i = 0; i < n; ++i) {
    int64_t* vo = val_off + i * k;
    int32_t* vl = val_len + i * k;
    uint8_t* vt = val_type + i * k;
    for (long j = 0; j < k; ++j) {
      vo[j] = -1;
      vl[j] = 0;
      vt[j] = 0;
    }
    int64_t off = aux_off[i];
    const int64_t end = aux_end[i];
    long found = 0;
    while (off + 3 <= end && found < k) {
      const uint8_t t1 = buf[off];
      const uint8_t t2 = buf[off + 1];
      const uint8_t typ = buf[off + 2];
      off += 3;
      int64_t size = tag_fixed_size(typ);
      if (size == 0) {
        if (typ == 'Z' || typ == 'H') {
          const uint8_t* nul = static_cast<const uint8_t*>(
              std::memchr(buf + off, 0, static_cast<size_t>(end - off)));
          if (nul == nullptr) break;  // malformed: unterminated string
          size = (nul - (buf + off)) + 1;
        } else if (typ == 'B') {
          if (off + 5 > end) break;
          const int64_t esize = tag_fixed_size(buf[off]);
          if (esize == 0) break;
          size = 5 + esize * static_cast<int64_t>(read_u32(buf + off + 1));
        } else {
          break;  // unknown type: stop scanning this record
        }
      }
      if (off + size > end) break;
      for (long j = 0; j < k; ++j) {
        if (vo[j] < 0 && tags[2 * j] == t1 && tags[2 * j + 1] == t2) {
          vo[j] = off;
          vl[j] = static_cast<int32_t>(
              (typ == 'Z' || typ == 'H') ? size - 1 : size);
          vt[j] = typ;
          ++found;
        }
      }
      off += size;
    }
  }
}

// Group n records by equality of a byte range (e.g. an MI tag value or the
// CIGAR region): starts[g] = first record index of group g; returns the group
// count. A record with off < 0 (missing tag) returns -(i+1) so the caller can
// raise (iter_mi_groups raises on missing MI, core/grouper.py:38-41).
long fgumi_group_starts(const uint8_t* buf, const int64_t* off,
                        const int32_t* len, long n, int64_t* starts) {
  long g = 0;
  for (long i = 0; i < n; ++i) {
    if (off[i] < 0) return -(i + 1);
    if (i == 0 || len[i] != len[i - 1] ||
        std::memcmp(buf + off[i], buf + off[i - 1],
                    static_cast<size_t>(len[i])) != 0) {
      starts[g++] = i;
    }
  }
  return g;
}

// Batch SourceRead conversion (vanilla_caller.rs:940-1032 semantics; mirrors
// consensus/vanilla.py::_create_source_read with trim disabled): unpack 4-bit
// seq into base codes 0..4 + quals at codes/quals + i*stride, reverse-
// complement reverse-strand reads, mask q<min_q to N/Q2, clip `clip[i]` bases
// from the (oriented) end, trim trailing Ns. final_len[i] = surviving length,
// -1 for rejected reads (empty or all-0xFF quals). Row tails are padded N/0.
void fgumi_pack_reads(const uint8_t* buf, const int64_t* seq_off,
                      const int64_t* qual_off, const int32_t* l_seq,
                      const uint8_t* reverse, const int32_t* clip, long n,
                      int min_q, long stride, int mode, uint8_t* codes,
                      uint8_t* quals, int32_t* final_len) {
  // mode bit0: keep all-0xFF-quality reads (no -1 rejection); bit1: keep
  // trailing Ns (no final-length trim) — the CODEC SourceRead conversion
  // (codec_caller.rs:467-532) does neither of the vanilla post-steps.
  for (long i = 0; i < n; ++i) {
    uint8_t* crow = codes + i * stride;
    uint8_t* qrow = quals + i * stride;
    int64_t read_len = l_seq[i];
    if (read_len > stride) read_len = stride;
    if (read_len <= 0) {
      final_len[i] = -1;
      std::memset(crow, 4, static_cast<size_t>(stride));
      std::memset(qrow, 0, static_cast<size_t>(stride));
      continue;
    }
    const uint8_t* packed = buf + seq_off[i];
    const uint8_t* q = buf + qual_off[i];
    bool all_ff = (mode & 1) == 0;
    for (int64_t j = 0; all_ff && j < read_len; ++j) {
      if (q[j] != 0xFF) all_ff = false;
    }
    if (all_ff) {
      final_len[i] = -1;
      std::memset(crow, 4, static_cast<size_t>(stride));
      std::memset(qrow, 0, static_cast<size_t>(stride));
      continue;
    }
    if (reverse[i]) {
      // write reverse-complemented: output j <- input read_len-1-j
      for (int64_t j = 0; j < read_len; ++j) {
        const int64_t src = read_len - 1 - j;
        const uint8_t nib =
            (src & 1) ? (packed[src >> 1] & 0xF) : (packed[src >> 1] >> 4);
        const uint8_t code = kNib2Code[nib];
        crow[j] = code < 4 ? static_cast<uint8_t>(3 - code) : 4;
        qrow[j] = q[src];
      }
    } else {
      for (int64_t j = 0; j < read_len; ++j) {
        const uint8_t nib =
            (j & 1) ? (packed[j >> 1] & 0xF) : (packed[j >> 1] >> 4);
        crow[j] = kNib2Code[nib];
        qrow[j] = q[j];
      }
    }
    for (int64_t j = 0; j < read_len; ++j) {
      if (qrow[j] < min_q) {
        crow[j] = 4;
        qrow[j] = 2;
      }
    }
    int64_t final_n = read_len - clip[i];
    if (final_n < 0) final_n = 0;
    if (!(mode & 2)) {
      while (final_n > 0 && crow[final_n - 1] == 4) --final_n;
    }
    final_len[i] = static_cast<int32_t>(final_n);
    if (final_n < stride) {
      std::memset(crow + final_n, 4, static_cast<size_t>(stride - final_n));
      std::memset(qrow + final_n, 0, static_cast<size_t>(stride - final_n));
    }
  }
}

// Batch mate-overlap clip counts (overlap.rs:117-140 via the MC tag; mirrors
// core/overlap.py::num_bases_extending_past_mate). mc_off/mc_len locate each
// record's MC tag value (-1 = absent -> clip 0).
void fgumi_mate_clips(const uint8_t* buf, const int64_t* cigar_off,
                      const int32_t* n_cigar, const int32_t* flag,
                      const int32_t* ref_id, const int32_t* pos,
                      const int32_t* next_ref_id, const int32_t* next_pos,
                      const int32_t* tlen, const int64_t* mc_off,
                      const int32_t* mc_len, long n, int32_t* clip) {
  for (long i = 0; i < n; ++i) {
    clip[i] = 0;
    const CigarView c{buf + cigar_off[i], n_cigar[i]};
    if (!is_fr_pair(flag[i], ref_id[i], next_ref_id[i], pos[i], next_pos[i],
                    tlen[i], c)) {
      continue;
    }
    if (mc_off[i] < 0) continue;
    int64_t lead = 0, rlen = 0, trail = 0;
    if (!parse_mc_cigar(buf + mc_off[i], mc_len[i], &lead, &rlen, &trail)) {
      continue;
    }
    const int64_t mate_pos = static_cast<int64_t>(next_pos[i]) + 1;
    clip[i] = static_cast<int32_t>(bases_extending_past_mate(
        c, flag[i], pos[i], mate_pos - lead, mate_pos - 1 + rlen + trail));
  }
}

// In-place overlapping-pair base correction on the chunk buffer (mirrors
// consensus/overlapping.py::OverlappingBasesConsensusCaller.call; reference
// overlapping.rs:80-345). r1_off/r2_off are the paired records' data offsets
// (post-block_size). agreement: 0=consensus 1=max-qual 2=pass-through;
// disagreement: 0=consensus 1=mask-both 2=mask-lower-qual. stats (int64[4]):
// overlapping, agreeing, disagreeing, corrected.
void fgumi_overlap_correct_pairs(uint8_t* buf, const int64_t* r1_off,
                                 const int64_t* r2_off, long n_pairs,
                                 int agreement, int disagreement,
                                 int64_t* stats) {
  for (long p = 0; p < n_pairs; ++p) {
    const uint8_t* d1 = buf + r1_off[p];
    const uint8_t* d2 = buf + r2_off[p];
    const int32_t flag1 = read_u16(d1 + 14), flag2 = read_u16(d2 + 14);
    if ((flag1 | flag2) & kFlagUnmapped) continue;
    if (read_i32(d1) != read_i32(d2)) continue;  // ref_id mismatch
    const int32_t n_cig1 = read_u16(d1 + 12), n_cig2 = read_u16(d2 + 12);
    const int32_t l_seq1 = read_i32(d1 + 16), l_seq2 = read_i32(d2 + 16);
    const int64_t cig1_off = 32 + d1[8], cig2_off = 32 + d2[8];
    const CigarView c1{d1 + cig1_off, n_cig1};
    const CigarView c2{d2 + cig2_off, n_cig2};
    if (cigar_ref_len(c1) == 0 || cigar_ref_len(c2) == 0) continue;
    uint8_t* seq1 = buf + r1_off[p] + cig1_off + 4 * n_cig1;
    uint8_t* seq2 = buf + r2_off[p] + cig2_off + 4 * n_cig2;
    uint8_t* q1 = seq1 + (l_seq1 + 1) / 2;
    uint8_t* q2 = seq2 + (l_seq2 + 1) / 2;

    // Merge-walk the two reads' aligned (ref_pos, read_off) streams
    // (ReadMateAndRefPosIterator, overlapping.rs:560-620).
    int32_t i1 = 0, i2 = 0;            // cigar op indices
    int64_t ref1 = read_i32(d1 + 4) + 1, ref2 = read_i32(d2 + 4) + 1;
    int64_t off1 = 0, off2 = 0;        // read offsets
    int64_t rem1 = 0, rem2 = 0;        // remaining bases in current align op

    auto advance = [](const CigarView& c, int32_t& i, int64_t& ref_pos,
                      int64_t& read_off, int64_t& rem) {
      // position at the next aligned base; rem = bases left in this op
      while (rem == 0 && i < c.n) {
        const uint32_t op = c.op(i);
        const int64_t len = c.len(i);
        if (op_is_align(op)) {
          rem = len;
        } else if (op == 1 || op == 4) {  // I S
          read_off += len;
        } else if (op == 2 || op == 3) {  // D N
          ref_pos += len;
        }
        ++i;
      }
      return rem > 0;
    };

    while (true) {
      if (!advance(c1, i1, ref1, off1, rem1)) break;
      if (!advance(c2, i2, ref2, off2, rem2)) break;
      if (ref1 < ref2) {
        const int64_t skip = ref2 - ref1 < rem1 ? ref2 - ref1 : rem1;
        ref1 += skip; off1 += skip; rem1 -= skip;
        continue;
      }
      if (ref2 < ref1) {
        const int64_t skip = ref1 - ref2 < rem2 ? ref1 - ref2 : rem2;
        ref2 += skip; off2 += skip; rem2 -= skip;
        continue;
      }
      // ref1 == ref2: one overlapping aligned base
      const int64_t o1 = off1, o2 = off2;
      ref1 += 1; off1 += 1; rem1 -= 1;
      ref2 += 1; off2 += 1; rem2 -= 1;
      const uint8_t nib1 =
          (o1 & 1) ? (seq1[o1 >> 1] & 0xF) : (seq1[o1 >> 1] >> 4);
      const uint8_t nib2 =
          (o2 & 1) ? (seq2[o2 >> 1] & 0xF) : (seq2[o2 >> 1] >> 4);
      if (nib1 == 15 || nib2 == 15) continue;  // no-call skipped entirely
      ++stats[0];
      const int32_t qa = q1[o1], qb = q2[o2];
      auto write_nib = [](uint8_t* seq, int64_t o, uint8_t nib) {
        if (o & 1) {
          seq[o >> 1] = (seq[o >> 1] & 0xF0) | nib;
        } else {
          seq[o >> 1] = (seq[o >> 1] & 0x0F) | (nib << 4);
        }
      };
      if (nib1 == nib2) {
        ++stats[1];
        if (agreement == 2) continue;  // pass-through
        const int32_t new_q =
            agreement == 0 ? (qa + qb < 93 ? qa + qb : 93)
                           : (qa > qb ? qa : qb);
        if (new_q != qa || new_q != qb) ++stats[3];
        q1[o1] = static_cast<uint8_t>(new_q);
        q2[o2] = static_cast<uint8_t>(new_q);
      } else {
        ++stats[2];
        if (disagreement == 0) {  // consensus: higher qual wins by difference
          if (qa == qb) {
            write_nib(seq1, o1, 15);
            write_nib(seq2, o2, 15);
            q1[o1] = 2;
            q2[o2] = 2;
          } else {
            const uint8_t win_nib = qa > qb ? nib1 : nib2;
            const int32_t dq = qa > qb ? qa - qb : qb - qa;
            const uint8_t new_q = static_cast<uint8_t>(dq > 2 ? dq : 2);
            write_nib(seq1, o1, win_nib);
            write_nib(seq2, o2, win_nib);
            q1[o1] = new_q;
            q2[o2] = new_q;
          }
          stats[3] += 2;
        } else if (disagreement == 1) {  // mask-both
          write_nib(seq1, o1, 15);
          write_nib(seq2, o2, 15);
          q1[o1] = 2;
          q2[o2] = 2;
          stats[3] += 2;
        } else {  // mask-lower-qual; tie masks both
          if (qa <= qb) {
            write_nib(seq1, o1, 15);
            q1[o1] = 2;
            ++stats[3];
          }
          if (qb <= qa) {
            write_nib(seq2, o2, 15);
            q2[o2] = 2;
            ++stats[3];
          }
        }
      }
    }
  }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Batched FASTQ -> unmapped-BAM extraction (the hot half of `extract`).
// Reference analog: the SIMD FASTQ lexer + parallel Decode of the FASTQ
// pipeline (crates/fgumi-simd-fastq/src/lib.rs:1-13;
// src/lib/unified_pipeline/fastq.rs Decode step) and the UnmappedSamBuilder
// record assembly (extract.rs:887-980). One call consumes one aligned batch
// of records across all FASTQ inputs and emits block_size-prefixed BAM wire
// bytes covering the common tag set (RG:Z, RX:Z, QX:Z); exotic options
// (cell/sample barcodes, single-tag, name annotation) stay on the Python
// path (commands/extract.py make_records).
//
// Segments: flattened read-structure ops in emission order. kind: 0=template
// 1=UMI(M) 2=skip(S). seg_len -1 means "rest of the read". UMI segments join
// with '-' (quals with ' ') across all inputs, fgbio style.
//
// Returns records written; negative = error: -1 out_cap too small,
// -2 read-name mismatch at state[1], -3 read too short at state[1].
// state[0] = bytes written.

namespace {

struct NibInit {
  uint8_t t[256];
  NibInit() {
    // full BAM nibble alphabet "=ACMGRSVTWYHKDBN" (matches io/bam.py
    // BASE_TO_NIBBLE; unknown bytes encode as N)
    const char* alpha = "=ACMGRSVTWYHKDBN";
    for (int i = 0; i < 256; ++i) t[i] = 15;
    for (int v = 0; v < 16; ++v) {
      t[static_cast<uint8_t>(alpha[v])] = static_cast<uint8_t>(v);
      t[static_cast<uint8_t>(alpha[v] | 0x20)] = static_cast<uint8_t>(v);
    }
  }
};
const NibInit kNib;

inline long strip_name(const uint8_t* name, long len) {
  long n = len;
  for (long i = 0; i < n; ++i) {
    if (name[i] == ' ' || name[i] == '\t') { n = i; break; }
  }
  if (n >= 2 && name[n - 2] == '/' && name[n - 1] >= '0' && name[n - 1] <= '9')
    n -= 2;
  return n;
}

}  // namespace

extern "C" {

long fgumi_extract_records(
    long n_inputs, long n_records, const int64_t* buf_addr,
    const int64_t* name_off, const int32_t* name_len, const int64_t* seq_off,
    const int32_t* seq_len, const int64_t* qual_off, long n_segs,
    const int32_t* seg_input, const int32_t* seg_kind, const int32_t* seg_len,
    int qual_offset, const uint8_t* rg, int rg_len, int store_umi_quals,
    uint8_t* out, long out_cap, int64_t* state) {
  long off = 0;
  uint8_t umi[1024];
  uint8_t umiq[1024];
  const uint8_t* tmpl_seq[8];
  const uint8_t* tmpl_qual[8];
  long tmpl_len[8];

  for (long r = 0; r < n_records; ++r) {
    // stripped-name agreement across inputs
    const uint8_t* name0 =
        reinterpret_cast<const uint8_t*>(buf_addr[0]) + name_off[r];
    long n0 = strip_name(name0, name_len[r]);
    for (long k = 1; k < n_inputs; ++k) {
      const uint8_t* nk = reinterpret_cast<const uint8_t*>(buf_addr[k]) +
                          name_off[k * n_records + r];
      long lk = strip_name(nk, name_len[k * n_records + r]);
      if (lk != n0 || memcmp(nk, name0, n0) != 0) {
        state[1] = r;
        return -2;
      }
    }

    // walk segments
    long umi_len = 0, umiq_len = 0, n_tmpl = 0;
    long cursor[8] = {0};
    for (long s = 0; s < n_segs; ++s) {
      const long k = seg_input[s];
      const long idx = k * n_records + r;
      const uint8_t* sbuf =
          reinterpret_cast<const uint8_t*>(buf_addr[k]) + seq_off[idx];
      const uint8_t* qbuf =
          reinterpret_cast<const uint8_t*>(buf_addr[k]) + qual_off[idx];
      const long total = seq_len[idx];
      long len = seg_len[s];
      if (len < 0) {
        len = total - cursor[k];
        if (len < 0) len = 0;
      } else if (cursor[k] + len > total) {
        state[1] = r;
        return -3;
      }
      const long at = cursor[k];
      cursor[k] += len;
      if (seg_kind[s] == 1) {  // UMI
        if (umi_len + len + 1 > static_cast<long>(sizeof(umi))) {
          state[1] = r;
          return -3;
        }
        if (umi_len) { umi[umi_len++] = '-'; umiq[umiq_len++] = ' '; }
        memcpy(umi + umi_len, sbuf + at, len);
        umi_len += len;
        memcpy(umiq + umiq_len, qbuf + at, len);
        umiq_len += len;
      } else if (seg_kind[s] == 0) {  // template
        if (n_tmpl >= 8) { state[1] = r; return -3; }
        tmpl_seq[n_tmpl] = sbuf + at;
        tmpl_qual[n_tmpl] = qbuf + at;
        tmpl_len[n_tmpl] = len;
        ++n_tmpl;
      }  // skip: nothing
    }

    // emit one record per template
    for (long t = 0; t < n_tmpl; ++t) {
      const uint8_t* seq = tmpl_seq[t];
      const uint8_t* qual = tmpl_qual[t];
      long L = tmpl_len[t];
      const uint8_t one_n[1] = {'N'};
      int empty = (L == 0);
      if (empty) { seq = one_n; L = 1; }  // qual emitted as literal Q2 below

      uint32_t flag = 0x4;  // unmapped
      if (n_tmpl == 2)
        flag |= 0x1u | 0x8u | (t == 0 ? 0x40u : 0x80u);

      const long nlen = n0;
      if (nlen + 1 > 255) {  // l_read_name is u8 (RecordBuilder parity)
        state[1] = r;
        return -4;
      }
      long need = 4 + 32 + nlen + 1 + (L + 1) / 2 + L;
      need += 3 + rg_len + 1;
      if (umi_len) need += 3 + umi_len + 1;
      if (umi_len && store_umi_quals) need += 3 + umiq_len + 1;
      if (off + need > out_cap) return -1;

      uint8_t* rec = out + off + 4;
      put_u32(rec + 0, 0xFFFFFFFFu);
      put_u32(rec + 4, 0xFFFFFFFFu);
      rec[8] = static_cast<uint8_t>(nlen + 1);
      rec[9] = 0;                    // mapq
      rec[10] = 0x48;                // bin 4680 lo
      rec[11] = 0x12;                // bin 4680 hi
      rec[12] = 0;                   // n_cigar lo
      rec[13] = 0;
      rec[14] = static_cast<uint8_t>(flag & 0xFF);
      rec[15] = static_cast<uint8_t>(flag >> 8);
      put_u32(rec + 16, static_cast<uint32_t>(L));
      put_u32(rec + 20, 0xFFFFFFFFu);
      put_u32(rec + 24, 0xFFFFFFFFu);
      put_u32(rec + 28, 0);
      uint8_t* p = rec + 32;
      memcpy(p, name0, nlen);
      p += nlen;
      *p++ = 0;
      // 4-bit packed sequence
      for (long i = 0; i + 1 < L; i += 2)
        *p++ = static_cast<uint8_t>((kNib.t[seq[i]] << 4) | kNib.t[seq[i + 1]]);
      if (L & 1) *p++ = static_cast<uint8_t>(kNib.t[seq[L - 1]] << 4);
      // saturating qual subtract (extract.rs:256-261)
      if (empty) {
        *p++ = 2;
      } else {
        for (long i = 0; i < L; ++i)
          *p++ = qual[i] >= qual_offset
                     ? static_cast<uint8_t>(qual[i] - qual_offset)
                     : 0;
      }
      // tags
      p[0] = 'R'; p[1] = 'G'; p[2] = 'Z';
      memcpy(p + 3, rg, rg_len);
      p += 3 + rg_len;
      *p++ = 0;
      if (umi_len) {
        p[0] = 'R'; p[1] = 'X'; p[2] = 'Z';
        memcpy(p + 3, umi, umi_len);
        p += 3 + umi_len;
        *p++ = 0;
        if (store_umi_quals) {
          p[0] = 'Q'; p[1] = 'X'; p[2] = 'Z';
          memcpy(p + 3, umiq, umiq_len);
          p += 3 + umiq_len;
          *p++ = 0;
        }
      }
      const long rec_len = p - rec;
      put_u32(out + off, static_cast<uint32_t>(rec_len));
      off += 4 + rec_len;
    }
  }
  state[0] = off;
  return n_records;
}

// Per-record aux tag names (u16 little-endian pairs) — the zipper engine
// needs the unmapped record's tag-name set to build per-record drop lists
// (zipper.rs merge_raw removes every same-named mapped tag before copying).
// counts[i] = names found, or -1 when > max_per or malformed (caller falls
// back to the per-record path).
void fgumi_tag_name_list(const uint8_t* buf, const int64_t* aux_off,
                         const int64_t* aux_end, long n, long max_per,
                         uint16_t* out_names, int32_t* counts) {
  for (long i = 0; i < n; ++i) {
    uint16_t* names = out_names + i * max_per;
    int64_t off = aux_off[i];
    const int64_t end = aux_end[i];
    long found = 0;
    bool bad = false;
    while (off + 3 <= end) {
      const uint16_t name = static_cast<uint16_t>(buf[off]) |
                            (static_cast<uint16_t>(buf[off + 1]) << 8);
      const uint8_t typ = buf[off + 2];
      off += 3;
      int64_t size = tag_fixed_size(typ);
      if (size == 0) {
        if (typ == 'Z' || typ == 'H') {
          const uint8_t* nul = static_cast<const uint8_t*>(
              std::memchr(buf + off, 0, static_cast<size_t>(end - off)));
          if (nul == nullptr) { bad = true; break; }
          size = (nul - (buf + off)) + 1;
        } else if (typ == 'B') {
          if (off + 5 > end) { bad = true; break; }
          const int64_t esize = tag_fixed_size(buf[off]);
          if (esize == 0) { bad = true; break; }
          size = 5 + esize * static_cast<int64_t>(read_u32(buf + off + 1));
        } else {
          bad = true;
          break;
        }
      }
      if (off + size > end) { bad = true; break; }
      if (found >= max_per) { bad = true; break; }
      names[found++] = name;
      off += size;
    }
    counts[i] = bad ? -1 : static_cast<int32_t>(found);
  }
}

// CIGAR strings for a whole batch ("*" for zero ops). Caller sizes out to
// sum(max(11 * n_cigar, 1)). Returns 0, or -1 on an invalid op code.
long fgumi_cigar_strings(const uint8_t* buf, const int64_t* cigar_off,
                         const int32_t* n_cigar, long n, uint8_t* out,
                         int64_t* out_off) {
  static const char kOps[] = "MIDNSHP=X";
  int64_t o = 0;
  out_off[0] = 0;
  for (long i = 0; i < n; ++i) {
    if (n_cigar[i] == 0) {
      out[o++] = '*';
    } else {
      const uint8_t* c = buf + cigar_off[i];
      for (int32_t k = 0; k < n_cigar[i]; ++k) {
        const uint32_t v = read_u32(c + 4 * k);
        const uint32_t op = v & 0xF;
        if (op > 8) return -1;
        uint32_t len = v >> 4;
        char digits[10];
        int nd = 0;
        do {
          digits[nd++] = static_cast<char>('0' + len % 10);
          len /= 10;
        } while (len != 0);
        while (nd > 0) out[o++] = digits[--nd];
        out[o++] = kOps[op];
      }
    }
    out_off[i + 1] = o;
  }
  return 0;
}

// Rebuild records with edited aux regions, in one pass (the native form of
// record_edit.TagEditor.finish: [prefix][surviving originals in order]
// [append blob]). drop lists are per-record u16 tag-name spans; appends are
// pre-encoded TLV bytes. Output records carry their block_size prefixes
// (write_serialized form), written contiguously; out_pos gets n+1 offsets.
// Returns total bytes, or -(i+1) on a malformed record i (caller falls
// back to the per-record editor).
long fgumi_rebuild_aux_records(
    const uint8_t* buf, const int64_t* data_off, const int64_t* aux_off,
    const int64_t* data_end, long n, const uint16_t* drop,
    const int64_t* drop_off, const uint8_t* appends, const int64_t* app_off,
    uint8_t* out, int64_t* out_pos) {
  int64_t o = 0;
  out_pos[0] = 0;
  for (long i = 0; i < n; ++i) {
    uint8_t* rec0 = out + o + 4;
    uint8_t* dst = rec0;
    const int64_t pre = aux_off[i] - data_off[i];
    memcpy(dst, buf + data_off[i], static_cast<size_t>(pre));
    dst += pre;
    const uint16_t* dr = drop + drop_off[i];
    const long nd = static_cast<long>(drop_off[i + 1] - drop_off[i]);
    int64_t off = aux_off[i];
    const int64_t end = data_end[i];
    while (off + 3 <= end) {
      const int64_t entry0 = off;
      const uint16_t name = static_cast<uint16_t>(buf[off]) |
                            (static_cast<uint16_t>(buf[off + 1]) << 8);
      const uint8_t typ = buf[off + 2];
      off += 3;
      int64_t size = tag_fixed_size(typ);
      if (size == 0) {
        if (typ == 'Z' || typ == 'H') {
          const uint8_t* nul = static_cast<const uint8_t*>(
              std::memchr(buf + off, 0, static_cast<size_t>(end - off)));
          if (nul == nullptr) return -(i + 1);
          size = (nul - (buf + off)) + 1;
        } else if (typ == 'B') {
          if (off + 5 > end) return -(i + 1);
          const int64_t esize = tag_fixed_size(buf[off]);
          if (esize == 0) return -(i + 1);
          size = 5 + esize * static_cast<int64_t>(read_u32(buf + off + 1));
        } else {
          return -(i + 1);
        }
      }
      if (off + size > end) return -(i + 1);
      off += size;
      bool dropped = false;
      for (long d = 0; d < nd; ++d) {
        if (dr[d] == name) { dropped = true; break; }
      }
      if (!dropped) {
        memcpy(dst, buf + entry0, static_cast<size_t>(off - entry0));
        dst += off - entry0;
      }
    }
    const int64_t alen = app_off[i + 1] - app_off[i];
    memcpy(dst, appends + app_off[i], static_cast<size_t>(alen));
    dst += alen;
    const int64_t rec_len = dst - rec0;
    put_u32(out + o, static_cast<uint32_t>(rec_len));
    o += 4 + rec_len;
    out_pos[i + 1] = o;
  }
  return o;
}

// Concatenate spans drawn from up to 8 source buffers (addresses in
// src_addrs) into one output blob — the varlen-assembly primitive the batch
// engines use to build per-record append regions without per-record Python.
// Zero-length spans are legal (disabled parts keep the span table
// rectangular). Returns total bytes; out_off gets n_spans+1 offsets.
long fgumi_concat_spans(const int64_t* src_addrs, const int32_t* src_id,
                        const int64_t* off, const int32_t* len, long n_spans,
                        uint8_t* out, int64_t* out_off) {
  int64_t o = 0;
  out_off[0] = 0;
  for (long i = 0; i < n_spans; ++i) {
    const int32_t l = len[i];
    if (l > 0) {
      const uint8_t* src =
          reinterpret_cast<const uint8_t*>(src_addrs[src_id[i]]);
      memcpy(out + o, src + off[i], static_cast<size_t>(l));
      o += l;
    }
    out_off[i + 1] = o;
  }
  return o;
}

// Reference-span end (pos + reference-consumed CIGAR length, min 1) per
// record — the BAI builder's per-record geometry without RawRecord
// round-trips (reference_length semantics of sort.rs BAI output).
void fgumi_ref_spans(const uint8_t* buf, const int64_t* cigar_off,
                     const int32_t* n_cigar, const int32_t* pos, long n,
                     int32_t* end_out) {
  for (long i = 0; i < n; ++i) {
    const uint8_t* c = buf + cigar_off[i];
    int64_t ref_len = 0;
    for (int32_t k = 0; k < n_cigar[i]; ++k) {
      const uint32_t v = read_u32(c + 4 * k);
      const uint32_t op = v & 0xF;
      // M, D, N, =, X consume reference
      if (op == 0 || op == 2 || op == 3 || op == 7 || op == 8) {
        ref_len += v >> 4;
      }
    }
    if (ref_len < 1) ref_len = 1;
    end_out[i] = pos[i] + static_cast<int32_t>(ref_len);
  }
}

// Compress src into consecutive complete BGZF blocks (0xFF00-byte payloads,
// reference InlineBgzfCompressor + the workers' parallel Compress step,
// base.rs:1123-1150). Blocks are independent, so n_threads > 1 compresses
// them in parallel into per-block bound-sized slots, then compacts. Returns
// total bytes written to dst; block_off receives n_blocks+1 offsets.
// dst must hold n_blocks * (compress_bound(0xFF00) + 26).
long fgumi_bgzf_compress_many(const uint8_t* src, long src_len, int level,
                              int n_threads, uint8_t* dst, long dst_cap,
                              long slot_bound, int64_t* block_off,
                              long* n_blocks_out) {
  constexpr long kBlock = 0xFF00;
  const long nb = (src_len + kBlock - 1) / kBlock;
  *n_blocks_out = nb;
  if (nb == 0) {
    block_off[0] = 0;
    return 0;
  }
  const long bound = slot_bound;  // caller-allocated per-block slot spacing
  if (bound < static_cast<long>(libdeflate_deflate_compress_bound(
                  nullptr, kBlock)) + 26 ||
      dst_cap < nb * bound) {
    return -2;
  }
  std::vector<long> sizes(static_cast<size_t>(nb), -1);
  auto work = [&](long t, long stride) {
    for (long i = t; i < nb; i += stride) {
      const long off = i * kBlock;
      const long len = src_len - off < kBlock ? src_len - off : kBlock;
      sizes[static_cast<size_t>(i)] = fgumi_bgzf_compress_block(
          src + off, len, level, dst + i * bound, bound);
    }
  };
  long threads = n_threads < 1 ? 1 : n_threads;
  if (threads > nb) threads = nb;
  if (threads <= 1) {
    work(0, 1);
  } else {
    std::vector<std::thread> pool;
    pool.reserve(static_cast<size_t>(threads));
    for (long t = 0; t < threads; ++t) pool.emplace_back(work, t, threads);
    for (auto& th : pool) th.join();
  }
  // compact the bound-spaced slots into a contiguous stream
  long o = 0;
  block_off[0] = 0;
  for (long i = 0; i < nb; ++i) {
    const long s = sizes[static_cast<size_t>(i)];
    if (s < 0) return -1;
    if (o != i * bound) memmove(dst + o, dst + i * bound,
                                static_cast<size_t>(s));
    o += s;
    block_off[i + 1] = o;
  }
  return o;
}

// --------------------------------------------------------------- sort engine
//
// Native internals of the external merge sort (reference:
// crates/fgumi-sort/src/radix.rs:35 MSD/LSD radix over packed keys,
// loser_tree.rs:34 k-way merge, codec.rs:7-8 spill codec). Keys here are the
// memcmp-ordered packed byte strings of fgumi_tpu/sort/keys.py; records are
// BAM wire bytes (block_size-prefixed). The Python layer holds contiguous
// key/record pools + span tables and calls:
//   fgumi_sort_spans  — argsort spans by (memcmp, ingest order)
//   fgumi_gather_spans — permute spans into one output blob
//   fgumi_write_run   — serialize a sorted run to disk (framed, deflate-1)
//   fgumi_merge_open/next/close — streaming k-way merge of runs

// argsort of n byte spans by (memcmp, index). A precomputed 8-byte
// big-endian prefix settles most comparisons in one u64 compare (the packed
// keys front-load tid/pos exactly so this works — keys.py's analog of the
// reference packing sort keys into fixed-width integers, keys.rs).
void fgumi_sort_spans(const uint8_t* keys, const int64_t* off,
                      const int32_t* len, long n, int64_t* perm) {
  std::vector<uint64_t> pfx(static_cast<size_t>(n));
  for (long i = 0; i < n; ++i) {
    const uint8_t* p = keys + off[i];
    const int l = len[i] < 8 ? len[i] : 8;
    uint64_t v = 0;
    for (int j = 0; j < l; ++j) v |= static_cast<uint64_t>(p[j]) << (56 - 8 * j);
    pfx[static_cast<size_t>(i)] = v;
  }
  for (long i = 0; i < n; ++i) perm[i] = i;
  std::sort(perm, perm + n, [&](int64_t a, int64_t b) {
    const uint64_t pa = pfx[static_cast<size_t>(a)];
    const uint64_t pb = pfx[static_cast<size_t>(b)];
    if (pa != pb) return pa < pb;
    const int32_t la = len[a], lb = len[b];
    if (la > 8 || lb > 8) {
      const int32_t l = la < lb ? la : lb;
      // first 8 bytes already known equal when both spans reach 8
      const int32_t skip = (la >= 8 && lb >= 8) ? 8 : 0;
      const int c = memcmp(keys + off[a] + skip, keys + off[b] + skip,
                           static_cast<size_t>(l - skip));
      if (c != 0) return c < 0;
      if (la != lb) return la < lb;
    }
    return a < b;  // ingest-order tiebreak makes the sort total (radix.rs:35)
  });
}

// Concatenate spans in permutation order into out (caller sizes out to
// sum(len)). Returns bytes written.
long fgumi_gather_spans(const uint8_t* src, const int64_t* off,
                        const int32_t* len, const int64_t* perm, long n,
                        uint8_t* out) {
  long o = 0;
  for (long i = 0; i < n; ++i) {
    const int64_t j = perm[i];
    memcpy(out + o, src + off[j], static_cast<size_t>(len[j]));
    o += len[j];
  }
  return o;
}

namespace {

// Spill-run entry header: [u16 klen][u32 rlen] then key bytes, record wire
// bytes. Frame header: [u32 compressed][u32 uncompressed]; zlib container
// (matches fgumi_zlib_* and the Python fallback codec).
constexpr long kRunEntryHeader = 6;

bool write_frame(FILE* f, const uint8_t* buf, long n, int level,
                 std::vector<uint8_t>* scratch) {
  errno = 0;  // a compression failure must not report a stale errno
  const size_t bound = libdeflate_zlib_compress_bound(
      compressor(level), static_cast<size_t>(n));
  if (scratch->size() < bound) scratch->resize(bound);
  const size_t c = libdeflate_zlib_compress(compressor(level), buf,
                                            static_cast<size_t>(n),
                                            scratch->data(), bound);
  if (c == 0) return false;
  uint8_t hdr[8];
  hdr[0] = c & 0xFF; hdr[1] = (c >> 8) & 0xFF;
  hdr[2] = (c >> 16) & 0xFF; hdr[3] = (c >> 24) & 0xFF;
  hdr[4] = n & 0xFF; hdr[5] = (n >> 8) & 0xFF;
  hdr[6] = (n >> 16) & 0xFF; hdr[7] = (n >> 24) & 0xFF;
  return fwrite(hdr, 1, 8, f) == 8 &&
         fwrite(scratch->data(), 1, c, f) == c;
}

}  // namespace

// Write one sorted spill run: entries in perm order, framed and compressed.
// Returns 0 on success, -errno on I/O failure (so the Python layer can map
// ENOSPC onto the resource clean-failure contract), -9999 on a
// compression/internal failure with no meaningful errno.
long fgumi_write_run(const uint8_t* path, const uint8_t* keys,
                     const int64_t* koff, const int32_t* klen,
                     const uint8_t* recs, const int64_t* roff,
                     const int32_t* rlen, const int64_t* perm, long n,
                     long frame_bytes, int level) {
  errno = 0;
  FILE* f = fopen(reinterpret_cast<const char*>(path), "wb");
  if (f == nullptr) return errno ? -errno : -9999;
  std::vector<uint8_t> frame;
  std::vector<uint8_t> scratch;
  frame.reserve(static_cast<size_t>(frame_bytes) + (64 << 10));
  bool ok = true;
  for (long i = 0; i < n && ok; ++i) {
    const int64_t j = perm[i];
    const uint32_t kl = static_cast<uint32_t>(klen[j]);
    const uint32_t rl = static_cast<uint32_t>(rlen[j]);
    uint8_t hdr[kRunEntryHeader];
    hdr[0] = kl & 0xFF; hdr[1] = (kl >> 8) & 0xFF;
    hdr[2] = rl & 0xFF; hdr[3] = (rl >> 8) & 0xFF;
    hdr[4] = (rl >> 16) & 0xFF; hdr[5] = (rl >> 24) & 0xFF;
    frame.insert(frame.end(), hdr, hdr + kRunEntryHeader);
    frame.insert(frame.end(), keys + koff[j], keys + koff[j] + kl);
    frame.insert(frame.end(), recs + roff[j], recs + roff[j] + rl);
    if (static_cast<long>(frame.size()) >= frame_bytes) {
      ok = write_frame(f, frame.data(), static_cast<long>(frame.size()),
                       level, &scratch);
      frame.clear();
    }
  }
  if (ok && !frame.empty()) {
    ok = write_frame(f, frame.data(), static_cast<long>(frame.size()), level,
                     &scratch);
  }
  if (fclose(f) != 0) ok = false;
  if (ok) return 0;
  // a failed fwrite/fclose leaves errno set (write_frame zeroes it before
  // compressing, so a pure compression failure reports -9999, not a stale
  // errno from an unrelated earlier syscall)
  return errno ? -errno : -9999;
}

namespace {

struct MergeState;  // fwd (prefetch pool lives on the merge state)

// One spill run being merged: streams frames, exposes the current entry.
// With a prefetch pool attached (fgumi_merge_open2) the NEXT frame's
// read+decompress runs on a worker thread while the heap consumes the
// current one — the reference work-steals spill decompression during the
// merge exactly like this (fgumi-sort/src/worker_pool.rs:25-31). Heap
// order is untouched: prefetch only changes WHEN a frame decodes, never
// which entry is next.
struct RunReader {
  FILE* f = nullptr;
  std::vector<uint8_t> frame;
  size_t pos = 0;
  bool eof = false;
  const uint8_t* key = nullptr;
  uint32_t klen = 0;
  const uint8_t* rec = nullptr;
  uint32_t rlen = 0;
  // prefetch slot (guarded by MergeState::mu; worker owns f while pending)
  MergeState* pf = nullptr;  // non-null once a pool is attached
  int idx = -1;
  std::vector<uint8_t> next_frame;
  bool next_eof = false;
  bool next_ok = true;
  // 0 = nothing scheduled, 1 = queued (stealable by the merge thread),
  // 2 = ready, 3 = decoding on a worker
  int pf_state = 0;

  // Read+decompress one frame into (dst, dst_eof). Returns false on
  // corrupt input. Thread-safe per run: only one reader (worker OR merge
  // thread) touches f at a time.
  bool read_frame_into(std::vector<uint8_t>* dst, bool* dst_eof) {
    uint8_t hdr[8];
    *dst_eof = false;
    if (fread(hdr, 1, 8, f) != 8) {
      *dst_eof = true;
      return true;  // clean EOF
    }
    const uint32_t c = read_u32(hdr);
    const uint32_t u = read_u32(hdr + 4);
    std::vector<uint8_t> comp(c);
    if (fread(comp.data(), 1, c, f) != c) return false;
    dst->resize(u);
    size_t actual = 0;
    const libdeflate_result r = libdeflate_zlib_decompress(
        decompressor(), comp.data(), c, dst->data(), u, &actual);
    return r == LIBDEFLATE_SUCCESS && actual == u;
  }

  bool load_frame();  // defined after MergeState (uses the pool)

  // Advance to the next entry; false on corrupt input (eof flag on clean end).
  bool next() {
    if (pos >= frame.size()) {
      if (!load_frame()) return false;
      if (eof) return true;
    }
    if (pos + kRunEntryHeader > frame.size()) return false;
    const uint8_t* p = frame.data() + pos;
    klen = read_u16(p);
    rlen = read_u32(p + 2);
    pos += kRunEntryHeader;
    if (pos + klen + rlen > frame.size()) return false;
    key = frame.data() + pos;
    rec = frame.data() + pos + klen;
    pos += klen + rlen;
    return true;
  }
};

struct MergeState {
  std::vector<RunReader> runs;
  std::vector<int> heap;  // indices into runs, min-heap by (key, run index)

  // ---- frame prefetch pool (empty = fully synchronous merge) ----
  std::vector<std::thread> pool;
  std::deque<int> work;
  std::mutex mu;
  std::condition_variable work_cv;  // workers: work arrived / stopping
  std::condition_variable done_cv;  // merge thread: a frame became ready
  bool stopping = false;
  long max_prefetch = 0;  // frame-slot budget across all runs
  long slots = 0;         // pending + ready (unconsumed) prefetched frames

  // call with mu held; silently skips when the budget is spent (the merge
  // thread then loads that run's frame inline — bounded memory, no
  // deadlock, identical output)
  void schedule_locked(int i) {
    RunReader& r = runs[static_cast<size_t>(i)];
    if (r.pf_state != 0 || r.eof || slots >= max_prefetch) return;
    slots += 1;
    r.pf_state = 1;
    work.push_back(i);
    work_cv.notify_one();
  }

  void worker_loop() {
    for (;;) {
      int i;
      {
        std::unique_lock<std::mutex> lk(mu);
        work_cv.wait(lk, [&] { return stopping || !work.empty(); });
        if (stopping) return;
        i = work.front();
        work.pop_front();
        // claim before decoding: the merge thread steals QUEUED (1)
        // frames back for inline decode, but waits for DECODING (3) ones
        runs[static_cast<size_t>(i)].pf_state = 3;
      }
      RunReader& r = runs[static_cast<size_t>(i)];
      const bool ok = r.read_frame_into(&r.next_frame, &r.next_eof);
      {
        std::lock_guard<std::mutex> lk(mu);
        r.next_ok = ok;
        r.pf_state = 2;
        done_cv.notify_all();
      }
    }
  }

  void start_pool(int n_threads, long max_frames) {
    max_prefetch = max_frames;
    for (int i = 0; i < static_cast<int>(runs.size()); ++i) {
      runs[static_cast<size_t>(i)].pf = this;
      runs[static_cast<size_t>(i)].idx = i;
    }
    {
      std::lock_guard<std::mutex> lk(mu);
      for (int i = 0; i < static_cast<int>(runs.size()); ++i) {
        schedule_locked(i);
      }
    }
    for (int t = 0; t < n_threads; ++t) {
      pool.emplace_back([this] { worker_loop(); });
    }
  }

  void stop_pool() {
    if (pool.empty()) return;
    {
      std::lock_guard<std::mutex> lk(mu);
      stopping = true;
      work_cv.notify_all();
    }
    for (std::thread& t : pool) t.join();
    pool.clear();
  }

  // (key, run index) — runs are ingest-ordered chunks, so the run-index
  // tiebreak reproduces the global ingest-ordinal total order the Python
  // sorter used (external.py sorted_records)
  bool less(int a, int b) const {
    const RunReader& ra = runs[a];
    const RunReader& rb = runs[b];
    const uint32_t l = ra.klen < rb.klen ? ra.klen : rb.klen;
    const int c = memcmp(ra.key, rb.key, l);
    if (c != 0) return c < 0;
    if (ra.klen != rb.klen) return ra.klen < rb.klen;
    return a < b;
  }

  void sift_down(size_t i) {
    const size_t n = heap.size();
    while (true) {
      size_t best = i;
      const size_t l = 2 * i + 1, r = 2 * i + 2;
      if (l < n && less(heap[l], heap[best])) best = l;
      if (r < n && less(heap[r], heap[best])) best = r;
      if (best == i) return;
      std::swap(heap[i], heap[best]);
      i = best;
    }
  }

  void sift_up(size_t i) {
    while (i > 0) {
      const size_t p = (i - 1) / 2;
      if (!less(heap[i], heap[p])) return;
      std::swap(heap[i], heap[p]);
      i = p;
    }
  }
};

bool RunReader::load_frame() {
  if (pf == nullptr || pf->pool.empty()) {
    // synchronous path (fgumi_merge_open / no prefetch budget)
    const bool ok = read_frame_into(&frame, &eof);
    if (ok && !eof) pos = 0;
    return ok;
  }
  MergeState* st = pf;
  std::unique_lock<std::mutex> lk(st->mu);
  if (pf_state == 1) {
    // still queued: steal it back (reference worker_pool work-stealing) —
    // the merge thread must never idle behind a backlog of decodes for
    // runs it does not need yet
    for (auto it = st->work.begin(); it != st->work.end(); ++it) {
      if (*it == idx) {
        st->work.erase(it);
        break;
      }
    }
    st->slots -= 1;
    pf_state = 0;
  }
  if (pf_state == 0) {
    // nothing in flight for this run (budget gate or a steal): load
    // inline (off the lock — only this thread touches f when no prefetch
    // is pending), then try to schedule the frame after
    lk.unlock();
    const bool ok = read_frame_into(&frame, &eof);
    pos = 0;
    if (ok && !eof) {
      std::lock_guard<std::mutex> lk2(st->mu);
      st->schedule_locked(idx);
    }
    return ok;
  }
  st->done_cv.wait(lk, [&] { return pf_state == 2; });
  pf_state = 0;
  st->slots -= 1;
  if (!next_ok) return false;
  frame.swap(next_frame);
  eof = next_eof;
  pos = 0;
  if (!eof) st->schedule_locked(idx);
  return true;
}

}  // namespace

void fgumi_merge_close(void* handle);  // forward (used on open failure)

// Open a k-way merge over '\n'-joined run paths with an optional frame
// prefetch pool: n_threads workers read+decompress each run's next frame
// while the heap drains the current one, holding at most
// max_prefetch_frames decoded frames beyond the per-run current ones
// (the governor's merge-prefetch budget / frame size). Returns nullptr on
// failure.
void* fgumi_merge_open2(const uint8_t* paths, long paths_len, long n_runs,
                        int n_threads, long max_prefetch_frames) {
  MergeState* st = new MergeState();
  st->runs.resize(static_cast<size_t>(n_runs));
  long start = 0;
  long run = 0;
  for (long i = 0; i <= paths_len && run < n_runs; ++i) {
    if (i == paths_len || paths[i] == '\n') {
      std::string path(reinterpret_cast<const char*>(paths + start),
                       static_cast<size_t>(i - start));
      st->runs[static_cast<size_t>(run)].f = fopen(path.c_str(), "rb");
      if (st->runs[static_cast<size_t>(run)].f == nullptr) {
        fgumi_merge_close(st);
        return nullptr;
      }
      ++run;
      start = i + 1;
    }
  }
  for (int i = 0; i < static_cast<int>(st->runs.size()); ++i) {
    RunReader& r = st->runs[static_cast<size_t>(i)];
    if (!r.next()) {
      fgumi_merge_close(st);
      return nullptr;
    }
    if (!r.eof) {
      st->heap.push_back(i);
      st->sift_up(st->heap.size() - 1);
    }
  }
  if (n_threads > 0 && max_prefetch_frames > 0 && n_runs > 1) {
    st->start_pool(n_threads, max_prefetch_frames);
  }
  return st;
}

void* fgumi_merge_open(const uint8_t* paths, long paths_len, long n_runs) {
  return fgumi_merge_open2(paths, paths_len, n_runs, 0, 0);
}

// Emit merged records (wire bytes, concatenated) into out, up to cap bytes
// or max_recs records; per-record wire lengths land in rec_lens. Returns
// bytes written (0 = merge complete), -1 on corrupt input.
long fgumi_merge_next(void* handle, uint8_t* out, long cap, int32_t* rec_lens,
                      long max_recs, long* n_recs) {
  MergeState* st = static_cast<MergeState*>(handle);
  long o = 0;
  long emitted = 0;
  while (!st->heap.empty() && emitted < max_recs) {
    const int top = st->heap[0];
    RunReader& r = st->runs[static_cast<size_t>(top)];
    if (o + static_cast<long>(r.rlen) > cap) break;
    memcpy(out + o, r.rec, r.rlen);
    o += r.rlen;
    rec_lens[emitted++] = static_cast<int32_t>(r.rlen);
    if (!r.next()) return -1;
    if (r.eof) {
      st->heap[0] = st->heap.back();
      st->heap.pop_back();
    }
    if (!st->heap.empty()) st->sift_down(0);
  }
  *n_recs = emitted;
  return o;
}

void fgumi_merge_close(void* handle) {
  MergeState* st = static_cast<MergeState*>(handle);
  st->stop_pool();  // join workers before their FILE*s go away
  for (RunReader& r : st->runs) {
    if (r.f != nullptr) fclose(r.f);
  }
  delete st;
}

// ---------------------------------------------------------------------------
// f64 host consensus engine (the CPU-backend counterpart of the XLA segment
// kernel, ops/kernel.py). Bit-exact with the f64 oracle (ops/oracle.py —
// reference semantics: base_builder.rs:612-644,795-852) by construction:
//
//   * lane log-likelihoods are Kahan-accumulated in read order with the SAME
//     IEEE add/sub sequence as oracle.accumulate_likelihoods, on the SAME
//     host-precomputed f64 tables, so the per-position sums are bit-identical
//     (including -inf / NaN poisoning from Q0 observations);
//   * positions whose winner margin is provably saturated (min loser gap
//     >= g_sat, derived so the oracle's two-trials quick path must fire)
//     emit the winner by exact argmax and a CONSTANT quality precomputed by
//     the oracle from ln_error_pre_umi — no transcendentals in C++ at all;
//   * depth-1 and depth-2 positions resolve through lookup tables the
//     Python side generated by running the oracle itself on every (base,
//     qual[, base, qual]) pileup;
//   * everything else (borderline margins, ties, Q0/NaN flows) is returned
//     to Python as (flat index, 4 lane sums, 4 obs counts) and recomputed by
//     the vectorized oracle epilogue, which IS the parity definition.
//
// codes/quals: dense (N, L) uint8 read rows, N = starts[J]; code 4 = N/pad
// (skipped). correct_tab/err_alt_tab: the f64 per-qual tables (index 0..93).
// Outputs are (J, L). Returns the number of slow positions encountered; only
// the first slow_cap are written to slow_idx/slow_ll/slow_obs, so a return
// value > slow_cap means the caller must retry with larger buffers.
long fgumi_consensus_segments(
    const uint8_t* codes, const uint8_t* quals, const int64_t* starts,
    long J, long L, const double* correct_tab, const double* err_alt_tab,
    double g_sat, int qual_const, int min_phred, const uint8_t* tab1_winner,
    const uint8_t* tab1_qual, const uint8_t* tab2_winner,
    const uint8_t* tab2_qual, uint8_t* out_winner, uint8_t* out_qual,
    int32_t* out_depth, int32_t* out_errors, int64_t* slow_idx,
    double* slow_ll, int32_t* slow_obs, long slow_cap) {
  struct PosAcc {
    double sum[4];
    double comp[4];
    int32_t obs[4];
    uint8_t b0, q0, b1, q1;  // first two observations (depth-table keys)
  };
  std::vector<PosAcc> acc(static_cast<size_t>(L));
  long n_slow = 0;
  for (long j = 0; j < J; ++j) {
    std::memset(acc.data(), 0, sizeof(PosAcc) * static_cast<size_t>(L));
    for (int64_t r = starts[j]; r < starts[j + 1]; ++r) {
      const uint8_t* crow = codes + r * L;
      const uint8_t* qrow = quals + r * L;
      for (long i = 0; i < L; ++i) {
        const uint8_t c = crow[i];
        if (c >= 4) continue;
        PosAcc& a = acc[static_cast<size_t>(i)];
        const uint8_t q = qrow[i] > 93 ? 93 : qrow[i];
        const double vc = correct_tab[q];
        const double ve = err_alt_tab[q];
        for (int lane = 0; lane < 4; ++lane) {
          // Kahan step, op-for-op oracle.accumulate_likelihoods
          const double v = (lane == c) ? vc : ve;
          const double y = v - a.comp[lane];
          const double t = a.sum[lane] + y;
          a.comp[lane] = (t - a.sum[lane]) - y;
          a.sum[lane] = t;
        }
        const int32_t n = a.obs[0] + a.obs[1] + a.obs[2] + a.obs[3];
        if (n == 0) {
          a.b0 = c;
          a.q0 = q;
        } else if (n == 1) {
          a.b1 = c;
          a.q1 = q;
        }
        ++a.obs[c];
      }
    }
    for (long i = 0; i < L; ++i) {
      const PosAcc& a = acc[static_cast<size_t>(i)];
      const int32_t depth = a.obs[0] + a.obs[1] + a.obs[2] + a.obs[3];
      const long o = j * L + i;
      if (depth == 0) {  // all-N column: no-observation no-call
        out_winner[o] = 4;
        out_qual[o] = static_cast<uint8_t>(min_phred);
        out_depth[o] = 0;
        out_errors[o] = 0;
        continue;
      }
      if (depth == 1) {
        const int k = a.b0 * 94 + a.q0;
        const uint8_t w = tab1_winner[k];
        out_winner[o] = w;
        out_qual[o] = tab1_qual[k];
        out_depth[o] = 1;
        out_errors[o] = (w == a.b0) ? 0 : 1;
        continue;
      }
      // q == 0 observations poison the Kahan compensation with -inf/NaN in
      // an order-dependent way; those pairs flow through the general sums
      // (bit-exact either way) to the oracle instead of the table.
      if (depth == 2 && a.q0 > 0 && a.q1 > 0) {
        const long k = static_cast<long>(a.b0 * 94 + a.q0) * 376 +
                       (a.b1 * 94 + a.q1);
        const uint8_t w = tab2_winner[k];
        out_winner[o] = w;
        out_qual[o] = tab2_qual[k];
        out_depth[o] = 2;
        out_errors[o] =
            2 - ((w < 4) ? ((w == a.b0) + (w == a.b1)) : 0);
        continue;
      }
      bool has_nan = false;
      for (int lane = 0; lane < 4; ++lane) {
        if (std::isnan(a.sum[lane])) {
          has_nan = true;
          break;
        }
      }
      if (!has_nan) {
        int wl = 0;
        double mx = a.sum[0];
        for (int lane = 1; lane < 4; ++lane) {
          if (a.sum[lane] > mx) {  // strict >: first-occurrence argmax
            mx = a.sum[lane];
            wl = lane;
          }
        }
        double second = -INFINITY;
        for (int lane = 0; lane < 4; ++lane) {
          if (lane != wl && a.sum[lane] > second) second = a.sum[lane];
        }
        if (std::isfinite(mx) && mx - second >= g_sat) {
          out_winner[o] = static_cast<uint8_t>(wl);
          out_qual[o] = static_cast<uint8_t>(qual_const);
          out_depth[o] = depth;
          out_errors[o] = depth - a.obs[wl];
          continue;
        }
      }
      if (n_slow < slow_cap) {
        slow_idx[n_slow] = o;
        for (int lane = 0; lane < 4; ++lane) {
          slow_ll[n_slow * 4 + lane] = a.sum[lane];
          slow_obs[n_slow * 4 + lane] = a.obs[lane];
        }
      }
      ++n_slow;
    }
  }
  return n_slow;
}

// ---------------------------------------------------------------------------
// Hybrid classify + hard-column export (round 5). Resolves the EASY columns
// natively at byte-scan cost — depth-0 no-call, depth-1/2 oracle lookup
// tables, and unanimous saturated columns (single observed base, no Q0, gap
// = sum of per-obs deltas >= g_sat + slack, so the oracle's two-trials quick
// path provably fires and the quality is the precomputed constant) — and
// exports the remaining HARD columns as a compact column-major observation
// stream for the accelerator. On UMI pileups the hard fraction is a few
// percent of columns carrying most of the remaining likelihood compute, so
// the device gets the compute-worthy work at ~2 orders of magnitude fewer
// link bytes than shipping whole pileups (the ops/kernel.py hard-column
// dispatch; reference semantics: base_builder.rs:186-301 unanimous fast
// path generalized to an export boundary).
//
// Unlike fgumi_consensus_segments, no Kahan lane accumulation happens here:
// per observation the work is one delta-table load + add and a few byte
// ops. Correctness of the saturation test: the naive f64 sum of
// nonnegative deltas differs from the engine's Kahan lane-sum gap by
// <= n*eps*sum (~1e-9 at depth 1000), dwarfed by the 1e-6 slack; columns
// failing the slack by less go hard and are resolved exactly downstream.
//
// Outputs: out_* (J, L) written for easy columns only; hard columns land in
// hard_idx (flat j*L+i, ascending), hard_depth, hard_counts (4 per column),
// and the concatenated hard_codes/hard_quals streams (valid obs only,
// quals clamped to 93). Returns n_hard and writes the total obs count to
// n_obs_out; if n_hard > hard_cap or obs > obs_cap the export is partial
// and the caller must retry with the returned sizes.
long fgumi_consensus_classify(
    const uint8_t* codes, const uint8_t* quals, const int64_t* starts,
    long J, long L, const double* delta_tab, double g_sat, int qual_const,
    int min_phred, const uint8_t* tab1_winner, const uint8_t* tab1_qual,
    const uint8_t* tab2_winner, const uint8_t* tab2_qual,
    uint8_t* out_winner, uint8_t* out_qual, int32_t* out_depth,
    int32_t* out_errors, int64_t* hard_idx, int32_t* hard_depth,
    int32_t* hard_counts, uint8_t* hard_codes, uint8_t* hard_quals,
    long hard_cap, long obs_cap, int64_t* n_obs_out) {
  struct ColAcc {
    double sum_delta;
    int32_t obs[4];
    uint8_t b0, q0, b1, q1;  // first two observations (depth-table keys)
    uint8_t distinct;        // bitmask of observed bases
    uint8_t has_q0;
  };
  std::vector<ColAcc> acc(static_cast<size_t>(L));
  long n_hard = 0;
  int64_t n_obs = 0;
  for (long j = 0; j < J; ++j) {
    std::memset(acc.data(), 0, sizeof(ColAcc) * static_cast<size_t>(L));
    for (int64_t r = starts[j]; r < starts[j + 1]; ++r) {
      const uint8_t* crow = codes + r * L;
      const uint8_t* qrow = quals + r * L;
      for (long i = 0; i < L; ++i) {
        const uint8_t c = crow[i];
        if (c >= 4) continue;
        ColAcc& a = acc[static_cast<size_t>(i)];
        const uint8_t q = qrow[i] > 93 ? 93 : qrow[i];
        const int32_t n = a.obs[0] + a.obs[1] + a.obs[2] + a.obs[3];
        if (n == 0) {
          a.b0 = c;
          a.q0 = q;
        } else if (n == 1) {
          a.b1 = c;
          a.q1 = q;
        }
        a.sum_delta += delta_tab[q];
        a.distinct |= static_cast<uint8_t>(1u << c);
        a.has_q0 |= static_cast<uint8_t>(q == 0);
        ++a.obs[c];
      }
    }
    for (long i = 0; i < L; ++i) {
      const ColAcc& a = acc[static_cast<size_t>(i)];
      const int32_t depth = a.obs[0] + a.obs[1] + a.obs[2] + a.obs[3];
      const long o = j * L + i;
      if (depth == 0) {  // all-N column: no-observation no-call
        out_winner[o] = 4;
        out_qual[o] = static_cast<uint8_t>(min_phred);
        out_depth[o] = 0;
        out_errors[o] = 0;
        continue;
      }
      if (depth == 1) {
        const int k = a.b0 * 94 + a.q0;
        const uint8_t w = tab1_winner[k];
        out_winner[o] = w;
        out_qual[o] = tab1_qual[k];
        out_depth[o] = 1;
        out_errors[o] = (w == a.b0) ? 0 : 1;
        continue;
      }
      if (depth == 2 && a.q0 > 0 && a.q1 > 0) {
        const long k = static_cast<long>(a.b0 * 94 + a.q0) * 376 +
                       (a.b1 * 94 + a.q1);
        const uint8_t w = tab2_winner[k];
        out_winner[o] = w;
        out_qual[o] = tab2_qual[k];
        out_depth[o] = 2;
        out_errors[o] = 2 - ((w < 4) ? ((w == a.b0) + (w == a.b1)) : 0);
        continue;
      }
      const bool unanimous = (a.distinct & (a.distinct - 1)) == 0;
      if (unanimous && !a.has_q0 && a.sum_delta >= g_sat + 1e-6) {
        out_winner[o] = a.b0;
        out_qual[o] = static_cast<uint8_t>(qual_const);
        out_depth[o] = depth;
        out_errors[o] = 0;
        continue;
      }
      // hard: export the column's valid observations (column-major gather
      // over the family's rows — the family block is cache-resident)
      if (n_hard < hard_cap && n_obs + depth <= obs_cap) {
        hard_idx[n_hard] = o;
        hard_depth[n_hard] = depth;
        for (int lane = 0; lane < 4; ++lane) {
          hard_counts[n_hard * 4 + lane] = a.obs[lane];
        }
        int64_t w = n_obs;
        for (int64_t r = starts[j]; r < starts[j + 1]; ++r) {
          const uint8_t c = codes[r * L + i];
          if (c >= 4) continue;
          const uint8_t q = quals[r * L + i];
          hard_codes[w] = c;
          hard_quals[w] = q > 93 ? 93 : q;
          ++w;
        }
        n_obs = w;
      } else {
        n_obs += depth;  // keep counting so the caller can size the retry
      }
      ++n_hard;
    }
  }
  *n_obs_out = n_obs;
  return n_hard;
}

// Elementwise CODEC duplex combine over the concatenated strand arrays —
// the single-pass form of consensus/codec.py combine_arrays (which mirrors
// the reference's codec_caller.rs:1127-1296 and stays the Python-side
// parity oracle on the classic path). Also accumulates the per-position
// both/disagree flags the caller previously derived with two extra passes.
// Depth/error inputs are int32; error sums run in int64 so extreme inputs
// cannot overflow (bit-parity with the numpy oracle holds for any inputs
// whose int32 sums don't wrap — the batch path pre-caps at I16_MAX, far
// inside that domain).
void fgumi_codec_combine(const uint8_t* b1, const uint8_t* b2,
                         const uint8_t* q1, const uint8_t* q2,
                         const int32_t* d1, const int32_t* d2,
                         const int32_t* e1, const int32_t* e2, int64_t n,
                         int32_t min_phred, uint8_t no_call,
                         uint8_t no_call_lower, int32_t i16_max,
                         uint8_t* cb, uint8_t* cq, int32_t* cd, int32_t* ce,
                         uint8_t* both_out, uint8_t* disag_out) {
  for (int64_t i = 0; i < n; ++i) {
    const int32_t ba = b1[i], bb = b2[i];
    const int32_t qa = q1[i], qb = q2[i];
    const bool a_has = ba != no_call && ba != no_call_lower;
    const bool b_has = bb != no_call && bb != no_call_lower;
    const bool both = a_has && b_has;
    const bool agree = both && ba == bb;
    const bool a_wins = both && !agree && qa > qb;
    const bool b_wins = both && !agree && qb > qa;
    const bool tie = both && !agree && qa == qb;

    int32_t raw_base = b_wins ? bb : ba;
    int32_t raw_qual;
    if (agree) {
      raw_qual = qa + qb > 93 ? 93 : qa + qb;
    } else if (a_wins) {
      raw_qual = qa - qb > min_phred ? qa - qb : min_phred;
    } else if (b_wins) {
      raw_qual = qb - qa > min_phred ? qb - qa : min_phred;
    } else if (tie) {
      raw_qual = min_phred;
    } else {
      raw_qual = 0;
    }
    const bool q_masked = both && raw_qual == min_phred;
    const int32_t dup_base = q_masked ? no_call : raw_base;
    const int32_t dup_qual = q_masked ? min_phred : raw_qual;

    const int32_t ca = d1[i] > i16_max ? i16_max : d1[i];
    const int32_t cbd = d2[i] > i16_max ? i16_max : d2[i];
    const int32_t dup_depth = ca + cbd;
    const bool chose_a = agree || a_wins || tie;
    int64_t dup_err;
    if (agree) {
      dup_err = static_cast<int64_t>(e1[i]) + e2[i];
    } else if (chose_a) {
      const int64_t t = static_cast<int64_t>(d2[i]) - e2[i];
      dup_err = e1[i] + (t > 0 ? t : 0);
    } else {
      const int64_t t = static_cast<int64_t>(d1[i]) - e1[i];
      dup_err = e2[i] + (t > 0 ? t : 0);
    }

    const bool only_a = a_has && !b_has;
    const bool only_b = b_has && !a_has;
    const bool a_q2 = qa == min_phred;
    const bool b_q2 = qb == min_phred;

    int32_t base, qual, depth;
    int64_t errors;
    if (both) {
      base = dup_base;
      qual = dup_qual;
      depth = dup_depth;
      errors = dup_err;
    } else if (only_a) {
      base = a_q2 ? no_call : ba;
      qual = a_q2 ? min_phred : qa;
      depth = d1[i];
      errors = e1[i];
    } else if (only_b) {
      base = b_q2 ? no_call : bb;
      qual = b_q2 ? min_phred : qb;
      depth = d2[i];
      errors = e2[i];
    } else {
      base = no_call;
      qual = min_phred;
      depth = 0;
      const int64_t s = static_cast<int64_t>(e1[i]) + e2[i];
      errors = s > i16_max ? i16_max : s;
    }

    const bool n_mask = ba == no_call || bb == no_call;
    cb[i] = static_cast<uint8_t>(n_mask ? no_call : base);
    cq[i] = static_cast<uint8_t>(n_mask ? min_phred : qual);
    cd[i] = depth > 2 * i16_max ? 2 * i16_max : depth;
    ce[i] = static_cast<int32_t>(errors > i16_max ? i16_max
                                                               : errors);
    both_out[i] = both ? 1 : 0;
    disag_out[i] = (a_wins || b_wins || tie) ? 1 : 0;
  }
}

// Duplex consensus-RX fast path (fast_duplex.py _output_rx): per output
// read, combine the a-seg RX (verbatim) and b-seg RX (strand-flipped =
// '-'-separated fields reversed) when BOTH contributing segs are unanimous
// (una_off >= 0) or absent (-1). Emits into `blob`:
//   total-present == 1  -> the single value verbatim
//   values all equal    -> the value with acgtn uppercased
// Anything else (divergent seg una_off == -2, or disagreeing values) is a
// python-fallback output: its index lands in fb_idx and rx_len stays 0.
// Returns the fallback count, or -1 if blob_cap would overflow (caller
// sizes blob_cap as the sum of both contributing lengths per output, so
// this is a programming-error guard, not a retry protocol).
int64_t fgumi_duplex_rx_fast(const uint8_t* buf, const int64_t* una_off,
                             const int32_t* una_len, const int64_t* cnt,
                             const int64_t* a_seg, const int64_t* b_seg,
                             int64_t K, uint8_t* blob, int64_t blob_cap,
                             int64_t* rx_off, int32_t* rx_len,
                             int64_t* fb_idx, int64_t* blob_used_out) {
  int64_t used = 0;
  int64_t n_fb = 0;
  uint8_t val[2][512];
  int32_t vlen[2];
  int64_t vcnt[2];
  for (int64_t k = 0; k < K; ++k) {
    rx_off[k] = 0;
    rx_len[k] = 0;
    int nv = 0;
    bool fallback = false;
    for (int side = 0; side < 2; ++side) {
      const int64_t s = side == 0 ? a_seg[k] : b_seg[k];
      if (s < 0 || una_off[s] == -1) continue;
      if (una_off[s] == -2 || una_len[s] > 512) {
        fallback = true;
        break;
      }
      const int32_t L = una_len[s];
      const uint8_t* src = buf + una_off[s];
      if (side == 0) {
        for (int32_t i = 0; i < L; ++i) val[nv][i] = src[i];
      } else {
        // strand flip: reverse the '-'-separated fields
        int32_t w = 0;
        int32_t end = L;
        for (int32_t i = L - 1; i >= -1; --i) {
          if (i == -1 || src[i] == '-') {
            for (int32_t j = i + 1; j < end; ++j) val[nv][w++] = src[j];
            if (i >= 0) val[nv][w++] = '-';
            end = i;
          }
        }
      }
      vlen[nv] = L;
      vcnt[nv] = cnt[s];
      ++nv;
    }
    if (fallback) {
      fb_idx[n_fb++] = k;
      continue;
    }
    if (nv == 0) continue;  // nothing to emit (rx_len stays 0)
    const int64_t total = nv == 2 ? vcnt[0] + vcnt[1] : vcnt[0];
    bool emit_upper;
    if (total == 1) {
      emit_upper = false;  // single read: verbatim
    } else if (nv == 2 && (vlen[0] != vlen[1] ||
                           memcmp(val[0], val[1], vlen[0]) != 0)) {
      fb_idx[n_fb++] = k;  // disagreeing unanimous values: likelihood call
      continue;
    } else {
      emit_upper = true;
    }
    const int32_t L = vlen[0];
    if (used + L > blob_cap) return -1;
    rx_off[k] = used;
    rx_len[k] = L;
    if (emit_upper) {
      for (int32_t i = 0; i < L; ++i) {
        const uint8_t c = val[0][i];
        blob[used + i] =
            (c == 'a' || c == 'c' || c == 'g' || c == 't' || c == 'n')
                ? c - 32 : c;
      }
    } else {
      memcpy(blob + used, val[0], L);
    }
    used += L;
  }
  *blob_used_out = used;
  return n_fb;
}

}  // extern "C"
