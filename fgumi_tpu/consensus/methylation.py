"""Methylation-aware consensus support (EM-Seq / TAPS).

Port of /root/reference/crates/fgumi-consensus/src/methylation.rs semantics,
in base-code space (0..4 = ACGTN):

- EM-Seq converts unmethylated C to T before PCR: at a reference-C position,
  C = methylated, T = converted (methylation.rs:1-11).
- TAPS converts methylated C to T: same counting, inverted MM/ML probability.
- Top strand tracks ref C with C/T evidence; bottom strand (reads stored
  reverse-complemented into read orientation) tracks ref G with G/A evidence.
- Consensus scoring sees normalized reads: converted bases are rewritten to the
  unconverted form at ref-C positions so conversions are not counted as errors
  (vanilla_caller.rs annotate_and_normalize).
- Output tags: MM:Z ("C+m,skips;" / "G-m,skips;") + ML:B:C probabilities,
  plus dense cu/ct i16 count arrays (methylation.rs:246-345).
"""

from dataclasses import dataclass

import numpy as np

from ..constants import N_CODE

I16_MAX = 32767

# base codes
A, C, G, T = 0, 1, 2, 3

EM_SEQ = "em-seq"
TAPS = "taps"


@dataclass
class MethylationAnnotation:
    """Per-consensus-position evidence (methylation.rs:23-80)."""

    is_ref_c: np.ndarray  # bool
    unconverted: np.ndarray  # int64
    converted: np.ndarray  # int64

    def truncate(self, length: int) -> "MethylationAnnotation":
        return MethylationAnnotation(self.is_ref_c[:length],
                                     self.unconverted[:length],
                                     self.converted[:length])

    def cu(self) -> np.ndarray:
        return np.minimum(self.unconverted, I16_MAX).astype(np.int16)

    def ct(self) -> np.ndarray:
        return np.minimum(self.converted, I16_MAX).astype(np.int16)


def is_top_strand(flags: int) -> bool:
    """Top strand iff R1 forward or R2 reverse (methylation.rs:370-383)."""
    from ..io.bam import FLAG_LAST, FLAG_REVERSE

    is_reverse = bool(flags & FLAG_REVERSE)
    is_r2 = bool(flags & FLAG_LAST)
    return is_reverse == is_r2


def query_to_ref_positions(simplified_cigar, alignment_start: int,
                           is_reverse: bool, original_cigar) -> list:
    """Per-query-position 0-based reference position (None = insertion).

    Reversed reads walk backward from the original CIGAR's alignment end
    (methylation.rs:105-185).
    """
    positions = []
    if is_reverse:
        ref_span = sum(n for op, n in original_cigar if op in "MDN=X")
        ref_pos = alignment_start + ref_span - 1
        step = -1
    else:
        ref_pos = alignment_start
        step = 1
    for op, n in simplified_cigar:
        if op in "M=X":
            for _ in range(n):
                positions.append(ref_pos)
                ref_pos += step
        elif op in "IS":
            positions.extend([None] * n)
        elif op in "DN":
            ref_pos += step * n
    return positions


def ref_codes_at_positions(ref_positions, ref_seq: bytes) -> np.ndarray:
    """uint8 base codes at mapped positions; N for insertions/out-of-range."""
    from ..constants import BASE_TO_CODE

    out = np.full(len(ref_positions), N_CODE, dtype=np.uint8)
    for i, p in enumerate(ref_positions):
        if p is not None and 0 <= p < len(ref_seq):
            out[i] = BASE_TO_CODE[ref_seq[p]]
    return out


def annotate(source_reads, ref_codes: np.ndarray,
             is_top: bool) -> MethylationAnnotation:
    """Count unconverted/converted evidence at ref-C positions
    (annotate_simplex_methylation, methylation.rs:186-244)."""
    length = len(ref_codes)
    ref_target, unconv, conv = (C, C, T) if is_top else (G, G, A)
    is_ref_c = ref_codes == ref_target
    unconverted = np.zeros(length, dtype=np.int64)
    converted = np.zeros(length, dtype=np.int64)
    for sr in source_reads:
        n = min(len(sr.codes), length)
        codes = sr.codes[:n]
        mask = is_ref_c[:n]
        unconverted[:n] += mask & (codes == unconv)
        converted[:n] += mask & (codes == conv)
    return MethylationAnnotation(is_ref_c=is_ref_c, unconverted=unconverted,
                                 converted=converted)


def normalize_source_reads(source_reads, annotation: MethylationAnnotation,
                           is_top: bool):
    """Rewrite converted bases to unconverted form at ref-C positions so
    consensus scoring treats conversion as agreement (vanilla_caller.rs
    annotate_and_normalize). Mutates the source reads' code arrays."""
    unconv, conv = (C, T) if is_top else (G, A)
    for sr in source_reads:
        n = min(len(sr.codes), len(annotation.is_ref_c))
        mask = annotation.is_ref_c[:n] & (sr.codes[:n] == conv)
        sr.codes[:n][mask] = unconv


def ref_bytes_for_alignment(cigar, pos: int, ref_seq, l_seq: int):
    """Per-query-position UPPERCASE reference byte as int32 (-1 for
    insertions/soft-clips/out-of-range), vectorized per CIGAR op — the one
    shared query->reference base resolver (resolve_ref_bases_for_record,
    fgumi-consensus filter.rs:1045-1118; also the zipper restore's walk)."""
    out = np.full(l_seq, -1, dtype=np.int32)
    qpos = 0
    rpos = pos
    for op, n in cigar:
        if op in "M=X":
            lo = max(rpos, 0)
            hi = min(rpos + n, len(ref_seq))
            if hi > lo and qpos + (lo - rpos) < l_seq:
                got = np.frombuffer(ref_seq[lo:hi],
                                    dtype=np.uint8).astype(np.int32)
                got = np.where((got >= 0x61) & (got <= 0x7a), got - 0x20, got)
                dst = qpos + (lo - rpos)
                take = min(len(got), l_seq - dst)
                out[dst:dst + take] = got[:take]
            qpos += n
            rpos += n
        elif op in "IS":
            qpos += n
        elif op in "DN":
            rpos += n
        if qpos >= l_seq:
            break
    return out


def combine_annotations(ab, ba, length: int) -> MethylationAnnotation:
    """Duplex combine: per-position count sums with OR'd ref-C flags over
    the truncated strand annotations; an absent strand contributes zeros
    (combine_methylation_annotations, methylation.rs:400-427)."""
    is_ref_c = np.zeros(length, dtype=bool)
    unconverted = np.zeros(length, dtype=np.int64)
    converted = np.zeros(length, dtype=np.int64)
    for ann in (ab, ba):
        if ann is None:
            continue
        n = min(length, len(ann.is_ref_c))
        is_ref_c[:n] |= ann.is_ref_c[:n]
        unconverted[:n] += ann.unconverted[:n]
        converted[:n] += ann.converted[:n]
    return MethylationAnnotation(is_ref_c=is_ref_c, unconverted=unconverted,
                                 converted=converted)


def build_mm_ml(consensus_codes: np.ndarray, annotation: MethylationAnnotation,
                is_top: bool, mode: str):
    """SAM MM:Z + ML:B:C tags, or None when no ref-C position carries evidence
    (methylation.rs:246-325)."""
    track = C if is_top else G
    skips = []
    probs = []
    skip = 0
    length = min(len(consensus_codes), len(annotation.is_ref_c))
    for i in range(length):
        if consensus_codes[i] != track:
            continue
        if annotation.is_ref_c[i]:
            total = int(annotation.unconverted[i]) + int(annotation.converted[i])
            if total > 0:
                num = int(annotation.unconverted[i]) if mode == EM_SEQ \
                    else int(annotation.converted[i])
                skips.append(skip)
                probs.append(min(num * 255 // total, 255))
                skip = 0
            else:
                skip += 1
        else:
            skip += 1
    if not skips:
        return None
    base_char, strand = ("C", "+") if is_top else ("G", "-")
    mm = f"{base_char}{strand}m," + ",".join(str(s) for s in skips) + ";"
    return mm, bytes(probs)
