"""CODEC consensus caller: one read-pair sequences both strands.

Mirrors /root/reference/crates/fgumi-consensus/src/codec_caller.rs:
- phase 1: keep paired primary reads; fragments rejected (codec_caller.rs:609-631);
- phase 2: pair R1/R2 by name; a template must be exactly one primary FR pair
  (symmetric per-pair test, codec_caller.rs:647-686); overlap clip amounts come
  from the mate record in hand, soft-only boundary (overlap.rs:156-165);
- phase 3: per-strand most-common-alignment filtering on clipped CIGARs
  (codec_caller.rs:722-738, 961-1002);
- phase 4: genomic-overlap geometry on the longest R1/R2 by reference length,
  min_duplex_length check, phase (indel) check, consensus length
  (codec_caller.rs:740-794, 1005-1062);
- phase 5: single-strand consensus per strand via the vanilla caller
  (min_reads=1, per-base tags, no masking/trim in SourceRead conversion,
  codec_caller.rs:378-402, 467-532, 796-847), RC one side, lowercase-'n' pad
  (codec_caller.rs:849-857, 1064-1116);
- duplex combine per position: agreement sums quality (cap Q93), disagreement
  takes the higher-quality base with the difference, ties keep base A at Q2;
  either-N masks; exact fgbio error accounting (codec_caller.rs:1118-1296);
- high-duplex-disagreement count/rate rejects are recoverable group drops
  (codec_caller.rs:99-141, 858-875);
- quality masks: outer bases assigned first, then single-strand regions
  (codec_caller.rs:1298-1345);
- output: single unmapped fragment with RG/MI/cD/cM/cE/aD/aM/aE/bD/bM/bE
  [+ad/bd/ae/be/ac/bc/aq/bq] [+CB] [+RX] (codec_caller.rs:1364-1539).

The single-strand hot loop runs on the batched TPU kernel through the shared
vanilla job machinery; geometry and the pairwise combine are vectorized host math.
"""

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..constants import (CODE_TO_BASE, MAX_PHRED, MIN_PHRED, N_CODE,
                         NO_CALL_BASE, NO_CALL_BASE_LOWER)
from ..core import cigar as cigar_utils
from ..core.overlap import (is_primary_fr_pair,
                            num_bases_extending_past_mate_vs_mate)
from ..io.bam import (FLAG_FIRST, FLAG_PAIRED, FLAG_REVERSE, FLAG_SECONDARY,
                      FLAG_SUPPLEMENTARY, FLAG_UNMAPPED, RawRecord,
                      RecordBuilder)
from ..ops.kernel import ConsensusKernel
from .simple_umi import consensus_umis
from .vanilla import (I16_MAX, R1, SourceRead, VanillaConsensusCaller,
                      VanillaOptions)

# ASCII complement preserving case ('n' pads survive RC, codec_caller.rs:1064-1073).
_ASCII_COMPLEMENT = np.arange(256, dtype=np.uint8)
for _a, _b in zip(b"ACGTacgt", b"TGCAtgca"):
    _ASCII_COMPLEMENT[_a] = _b


class DuplexDisagreementError(Exception):
    """Recoverable reject: the molecule exceeded duplex-disagreement limits."""

    def __init__(self, kind: str, value):
        self.kind = kind  # "count" | "rate"
        self.value = value
        super().__init__(f"High duplex disagreement {kind}: {value}")


@dataclass
class CodecOptions:
    """Mirrors CodecConsensusOptions defaults (codec_caller.rs:192-212)."""

    min_input_base_quality: int = 10
    error_rate_pre_umi: int = 45
    error_rate_post_umi: int = 40
    min_reads_per_strand: int = 1
    max_reads_per_strand: Optional[int] = None
    min_duplex_length: int = 1
    single_strand_qual: Optional[int] = None
    outer_bases_qual: Optional[int] = None
    outer_bases_length: int = 5
    max_duplex_disagreements: Optional[int] = None  # None = unlimited
    max_duplex_disagreement_rate: float = 1.0
    cell_tag: Optional[str] = None
    produce_per_base_tags: bool = False
    trim: bool = False
    min_consensus_base_quality: int = 0
    seed: int = 42


@dataclass
class CodecStats:
    """CodecConsensusStats analog (codec_caller.rs:214-259)."""

    total_input_reads: int = 0
    consensus_reads_generated: int = 0
    reads_filtered: int = 0
    consensus_reads_rejected_hdd: int = 0
    consensus_duplex_bases_emitted: int = 0
    duplex_disagreement_base_count: int = 0
    rejection_reasons: dict = field(default_factory=dict)

    def reject(self, reason: str, count: int):
        self.rejection_reasons[reason] = self.rejection_reasons.get(reason, 0) + count
        self.reads_filtered += count

    def duplex_disagreement_rate(self) -> float:
        if self.consensus_duplex_bases_emitted:
            return self.duplex_disagreement_base_count / self.consensus_duplex_bases_emitted
        return 0.0


@dataclass
class _SS:
    """Single-strand consensus in ASCII byte space (codec_caller.rs:261-284)."""

    bases: np.ndarray  # uint8 ASCII, 'n' = pad
    quals: np.ndarray  # uint8
    depths: np.ndarray  # int64
    errors: np.ndarray  # int64
    raw_read_count: int


def _rc_ss(ss: _SS) -> _SS:
    """Reverse-complement; depths/errors reverse with the bases (rs:557-578)."""
    return _SS(bases=_ASCII_COMPLEMENT[ss.bases[::-1]],
               quals=ss.quals[::-1].copy(), depths=ss.depths[::-1].copy(),
               errors=ss.errors[::-1].copy(), raw_read_count=ss.raw_read_count)


def combine_arrays(bases_a, bases_b, quals_a, quals_b, da, db, ea, eb):
    """Elementwise duplex-combine (rs:1127-1296), shared by the classic
    per-molecule `_combine` and the batch engine's concatenated pass
    (fast_codec.py `_finish_batch`) so the rules live in one place.

    Inputs are ASCII-base uint8 / qual uint8 / integer depth+error arrays of
    equal length (int64 on the classic path; the batch engine passes int32
    with values pre-capped at I16_MAX — sums here stay ~2x I16_MAX, so any
    int dtype >= int32 is safe); returns (base u8, qual u8, depth, errors,
    both, disag) with the either-strand N mask and the I16 caps applied.
    """
    ba, bb = bases_a.astype(np.int32), bases_b.astype(np.int32)
    qa, qb = quals_a.astype(np.int32), quals_b.astype(np.int32)

    a_has = (ba != NO_CALL_BASE) & (ba != NO_CALL_BASE_LOWER)
    b_has = (bb != NO_CALL_BASE) & (bb != NO_CALL_BASE_LOWER)
    both = a_has & b_has
    agree = both & (ba == bb)
    a_wins = both & ~agree & (qa > qb)
    b_wins = both & ~agree & (qb > qa)
    tie = both & ~agree & (qa == qb)

    raw_base = np.where(b_wins, bb, ba)  # agree/a_wins/tie keep base A
    # np.where chains, not np.select: select's broadcast machinery
    # dominated the per-molecule combine cost
    raw_qual = np.where(
        agree, np.minimum(93, qa + qb),
        np.where(a_wins, np.maximum(MIN_PHRED, qa - qb),
                 np.where(b_wins, np.maximum(MIN_PHRED, qb - qa),
                          np.where(tie, np.int32(MIN_PHRED),
                                   np.int32(0)))))
    # min-quality masking inside the duplex region (rs:1185-1190)
    q_masked = both & (raw_qual == MIN_PHRED)
    dup_base = np.where(q_masked, NO_CALL_BASE, raw_base)
    dup_qual = np.where(q_masked, MIN_PHRED, raw_qual)

    cap = lambda x: np.minimum(x, I16_MAX)
    dup_depth = cap(da) + cap(db)
    chose_a = agree | a_wins | tie
    dup_err = np.where(agree, ea + eb,
                       np.where(chose_a, ea + np.maximum(db - eb, 0),
                                eb + np.maximum(da - ea, 0)))

    only_a = a_has & ~b_has
    only_b = b_has & ~a_has
    a_q2 = qa == MIN_PHRED
    b_q2 = qb == MIN_PHRED

    base = np.where(
        both, dup_base,
        np.where(only_a, np.where(a_q2, NO_CALL_BASE, ba),
                 np.where(only_b, np.where(b_q2, NO_CALL_BASE, bb),
                          NO_CALL_BASE)))
    qual = np.where(
        both, dup_qual,
        np.where(only_a & ~a_q2, qa,
                 np.where(only_b & ~b_q2, qb, MIN_PHRED)))
    depth = np.where(both, dup_depth,
                     np.where(only_a, da, np.where(only_b, db, 0)))
    errors = np.where(both, dup_err,
                      np.where(only_a, ea,
                               np.where(only_b, eb, cap(ea + eb))))

    # either-strand uppercase-N mask, applied after rawBase math (rs:1253-1260)
    n_mask = (ba == NO_CALL_BASE) | (bb == NO_CALL_BASE)
    base = np.where(n_mask, NO_CALL_BASE, base).astype(np.uint8)
    qual = np.where(n_mask, MIN_PHRED, qual).astype(np.uint8)
    return (base, qual, np.minimum(depth, 2 * I16_MAX),
            np.minimum(errors, I16_MAX), both, a_wins | b_wins | tie)


def _pad_ss(ss: _SS, new_length: int, pad_left: bool) -> _SS:
    """Pad with lowercase 'n' / Q0 / depth 0 (rs:1064-1116)."""
    cur = len(ss.bases)
    if new_length <= cur:
        return ss
    n = new_length - cur
    pads = (np.full(n, NO_CALL_BASE_LOWER, dtype=np.uint8), np.zeros(n, np.uint8),
            np.zeros(n, np.int64), np.zeros(n, np.int64))
    arrays = (ss.bases, ss.quals, ss.depths, ss.errors)
    joined = [np.concatenate([p, a] if pad_left else [a, p])
              for p, a in zip(pads, arrays)]
    return _SS(*joined, raw_read_count=ss.raw_read_count)


@dataclass
class _ClippedInfo:
    """Per-record clip metadata (ClippedRecordInfo, codec_caller.rs:294-313)."""

    raw_idx: int
    clip_amount: int
    clip_from_start: bool
    clipped_seq_len: int
    clipped_cigar: list
    adjusted_pos: int  # 1-based, start-clip adjusted
    flags: int


class CodecConsensusCaller:
    """CODEC caller over MI groups; SS stage batched onto the TPU kernel."""

    def __init__(self, read_name_prefix: str, read_group_id: str,
                 options: CodecOptions = None, kernel: ConsensusKernel = None,
                 track_rejects: bool = False):
        self.options = options or CodecOptions()
        self.prefix = read_name_prefix
        self.read_group_id = read_group_id
        # SS delegation mirrors fgbio's ssCaller init (codec_caller.rs:378-402):
        # min_reads=1, per-base tags on, min consensus quality 0 (codec masks itself).
        ss_opts = VanillaOptions(
            error_rate_pre_umi=self.options.error_rate_pre_umi,
            error_rate_post_umi=self.options.error_rate_post_umi,
            min_input_base_quality=self.options.min_input_base_quality,
            min_reads=1, max_reads=None, produce_per_base_tags=True,
            seed=None, trim=False, min_consensus_base_quality=0)
        self.ss = VanillaConsensusCaller(read_name_prefix, read_group_id, ss_opts,
                                         kernel=kernel)
        self.kernel = self.ss.kernel
        self.stats = CodecStats()
        self._builder = RecordBuilder()
        self._counter = 0
        # Deterministic downsampling stream; the reference pins StdRng seed 42
        # (codec_caller.rs:376) — this build pins its own Philox stream.
        self._rng = np.random.Generator(np.random.Philox(key=self.options.seed))
        self.track_rejects = track_rejects
        self.rejected_reads = []

    # ------------------------------------------------------------ geometry prep

    def _build_clipped_info(self, rec: RawRecord, raw_idx: int,
                            clip_amount: int) -> _ClippedInfo:
        """build_clipped_info (codec_caller.rs:910-945)."""
        flg = rec.flag
        clip_from_start = bool(flg & FLAG_REVERSE)
        clipped_cigar, ref_consumed = cigar_utils.clip_cigar(
            rec.cigar(), clip_amount, clip_from_start)
        adjusted = rec.pos + 1 + (ref_consumed if clip_from_start else 0)
        return _ClippedInfo(
            raw_idx=raw_idx, clip_amount=clip_amount,
            clip_from_start=clip_from_start,
            clipped_seq_len=max(rec.l_seq - clip_amount, 0),
            clipped_cigar=clipped_cigar, adjusted_pos=adjusted, flags=flg)

    def _filter_most_common_alignment(self, infos: list) -> list:
        """Most-common-alignment filter on clipped CIGARs (rs:961-1002)."""
        if len(infos) < 2:
            return infos
        indexed = []
        for i, info in enumerate(infos):
            cig = cigar_utils.simplify(info.clipped_cigar)
            if info.flags & FLAG_REVERSE:
                cig = cigar_utils.reverse(cig)
            indexed.append((i, info.clipped_seq_len, cig))
        indexed.sort(key=lambda t: -t[1])
        keep = set(cigar_utils.select_most_common_alignment_group(indexed))
        rejected = len(infos) - len(keep)
        if rejected:
            self.stats.reject("MinorityAlignment", rejected)
        return [info for i, info in enumerate(infos) if i in keep]

    def _to_source_read(self, rec: RawRecord, idx: int,
                        info: _ClippedInfo) -> SourceRead:
        """to_source_read_for_codec_raw (rs:467-532): clip, RC if negative;
        no quality masking / trailing-N trim / quality trimming."""
        from ..constants import BASE_TO_CODE, reverse_complement_codes

        codes = BASE_TO_CODE[np.frombuffer(rec.seq_bytes(), dtype=np.uint8)]
        quals = rec.quals()
        clip = min(info.clip_amount, len(codes))
        if clip:
            if info.clip_from_start:
                codes, quals = codes[clip:], quals[clip:]
            else:
                codes, quals = codes[:-clip], quals[:-clip]
        simplified = cigar_utils.simplify(info.clipped_cigar)
        if info.flags & FLAG_REVERSE:
            codes = reverse_complement_codes(codes)
            quals = quals[::-1]
            simplified = cigar_utils.reverse(simplified)
        else:
            codes = codes.copy()
        return SourceRead(original_idx=idx, codes=codes, quals=quals.copy(),
                          simplified_cigar=simplified, flags=rec.flag)

    def prepare(self, records: list, umi: Optional[str] = None):
        """Phases 1-5 host prep for one MI group (consensus_reads_raw,
        codec_caller.rs:589-836). Returns a molecule dict with the two SS jobs,
        or None (rejected; reasons recorded). `umi` is the group key (from the
        grouping tag); falls back to the first record's MI tag."""
        self.stats.total_input_reads += len(records)
        if not records:
            return None
        if umi is None:
            umi = records[0].get_str(b"MI")

        # Phase 1: paired primary reads only.
        paired = []
        frag_count = 0
        for i, rec in enumerate(records):
            flg = rec.flag
            if not flg & FLAG_PAIRED:
                frag_count += 1
                continue
            if flg & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY):
                continue
            paired.append((i, rec))
        if frag_count:
            self.stats.reject("FragmentRead", frag_count)
        if not paired:
            return None

        # Phase 2: bucket by name (first-appearance order), require one FR pair.
        by_name = {}
        for i, rec in paired:
            by_name.setdefault(rec.name, []).append((i, rec))
        r1_infos, r2_infos = [], []
        for name, bucket in by_name.items():
            if len(bucket) != 2 or not is_primary_fr_pair(bucket[0][1], bucket[1][1]):
                self.stats.reject("NotPrimaryFrPair", len(bucket))
                continue
            (ia, a), (ib, b) = bucket
            (i1, rec1), (i2, rec2) = ((ia, a), (ib, b)) if a.flag & FLAG_FIRST \
                else ((ib, b), (ia, a))
            clip1 = num_bases_extending_past_mate_vs_mate(rec1, rec2)
            clip2 = num_bases_extending_past_mate_vs_mate(rec2, rec1)
            r1_infos.append(self._build_clipped_info(rec1, i1, clip1))
            r2_infos.append(self._build_clipped_info(rec2, i2, clip2))
        if not r1_infos:
            return None
        if len(r1_infos) < self.options.min_reads_per_strand:
            self.stats.reject("InsufficientReads", len(r1_infos) + len(r2_infos))
            return None

        # Downsample pairs (rs:701-720).
        max_pairs = self.options.max_reads_per_strand
        if max_pairs is not None and len(r1_infos) > max_pairs:
            idxs = sorted(self._rng.permutation(len(r1_infos))[:max_pairs])
            r1_infos = [r1_infos[i] for i in idxs]
            r2_infos = [r2_infos[i] for i in idxs]

        # Phase 3: per-strand alignment filtering.
        r1_infos = self._filter_most_common_alignment(r1_infos)
        r2_infos = self._filter_most_common_alignment(r2_infos)
        if not r1_infos or not r2_infos:
            return None
        if (len(r1_infos) < self.options.min_reads_per_strand
                or len(r2_infos) < self.options.min_reads_per_strand):
            self.stats.reject("InsufficientReads", len(r1_infos) + len(r2_infos))
            return None
        n_filtered = len(r1_infos) + len(r2_infos)

        # Phase 4: overlap geometry on the longest strands by reference length.
        ref_len = lambda info: cigar_utils.reference_length(info.clipped_cigar)
        longest_r1 = max(r1_infos, key=ref_len)  # first max (rs:742-751 rev-iter)
        longest_r2 = max(r2_infos, key=ref_len)
        r1_is_negative = bool(longest_r1.flags & FLAG_REVERSE)
        r2_is_negative = bool(longest_r2.flags & FLAG_REVERSE)
        longest_pos, longest_neg = ((longest_r2, longest_r1) if r1_is_negative
                                    else (longest_r1, longest_r2))
        overlap_start = longest_neg.adjusted_pos
        pos_end = longest_pos.adjusted_pos + max(ref_len(longest_pos) - 1, 0)
        duplex_length = pos_end - overlap_start + 1
        if duplex_length < self.options.min_duplex_length:
            self.stats.reject("InsufficientOverlap", n_filtered)
            return None

        # Phase check (rs:1005-1040): equal read-pos offsets at both ends.
        rp = lambda info, pos, last: cigar_utils.read_pos_at_ref_pos(
            info.clipped_cigar, info.adjusted_pos, pos, last)
        r1s, r2s = rp(longest_r1, overlap_start, True), rp(longest_r2, overlap_start, True)
        r1e, r2e = rp(longest_r1, pos_end, True), rp(longest_r2, pos_end, True)
        if None in (r1s, r2s, r1e, r2e) or (r1s - r2s) != (r1e - r2e):
            self.stats.reject("IndelErrorBetweenStrands", n_filtered)
            return None

        # Consensus length (rs:1042-1062).
        p = rp(longest_pos, pos_end, False)
        n_ = rp(longest_neg, pos_end, False)
        if p is None or n_ is None:
            self.stats.reject("IndelErrorBetweenStrands", n_filtered)
            return None
        consensus_length = p + longest_neg.clipped_seq_len - n_

        # Phase 5: SourceReads + SS jobs through the vanilla machinery.
        umi_str = umi or ""
        r1_sources = [self._to_source_read(records[info.raw_idx], i, info)
                      for i, info in enumerate(r1_infos)]
        r2_sources = [self._to_source_read(records[info.raw_idx], i, info)
                      for i, info in enumerate(r2_infos)]
        job_r1 = self.ss.job_from_source_reads(umi_str, R1, r1_sources)
        job_r2 = self.ss.job_from_source_reads(umi_str, R1, r2_sources)
        if job_r1 is None or job_r2 is None:
            return None

        return {
            "umi": umi, "records": records,
            "job_r1": job_r1, "job_r2": job_r2,
            "n_r1": len(r1_infos), "n_r2": len(r2_infos),
            "r1_is_negative": r1_is_negative, "r2_is_negative": r2_is_negative,
            "consensus_length": consensus_length,
            "source_raws": [records[info.raw_idx] for info in r1_infos + r2_infos],
        }

    # ------------------------------------------------------------ duplex combine

    def _combine(self, a: _SS, b: _SS):
        """Per-position duplex combine, vectorized (rs:1127-1296).

        Returns _SS; raises DuplexDisagreementError on threshold breach.
        """
        base, qual, depth, errors, both, disag = combine_arrays(
            a.bases, b.bases, a.quals, b.quals, a.depths, b.depths,
            a.errors, b.errors)

        duplex_bases = int(both.sum())
        disagreements = int(disag.sum())
        if duplex_bases:
            self.stats.consensus_duplex_bases_emitted += duplex_bases
            self.stats.duplex_disagreement_base_count += disagreements
            max_dd = self.options.max_duplex_disagreements
            if max_dd is not None and disagreements > max_dd:
                raise DuplexDisagreementError("count", disagreements)
            rate = disagreements / duplex_bases
            if rate > self.options.max_duplex_disagreement_rate:
                raise DuplexDisagreementError("rate", rate)

        return _SS(bases=base, quals=qual, depths=depth, errors=errors,
                   raw_read_count=a.raw_read_count + b.raw_read_count)

    def _mask_quals(self, consensus: _SS, padded_r1: _SS, padded_r2: _SS) -> _SS:
        """Outer-bases mask first, then single-strand regions (rs:1298-1345)."""
        opts = self.options
        length = len(consensus.quals)
        quals = consensus.quals.copy()
        if opts.outer_bases_length > 0 and opts.outer_bases_qual is not None:
            n = min(opts.outer_bases_length, length)
            quals[:n] = opts.outer_bases_qual
            quals[length - n:] = opts.outer_bases_qual
        if opts.single_strand_qual is not None:
            is_n = lambda x: (x == NO_CALL_BASE) | (x == NO_CALL_BASE_LOWER)
            ss_region = is_n(padded_r1.bases) | is_n(padded_r2.bases)
            quals[ss_region] = opts.single_strand_qual
        consensus.quals = quals
        return consensus

    # ------------------------------------------------------------ output

    def _build_record(self, consensus: _SS, ss_a: _SS, ss_b: _SS,
                      umi: Optional[str], source_raws: list,
                      all_records: list, rx_umis=None) -> bytes:
        """build_output_record_into (rs:1374-1539); tag order preserved.

        rx_umis: precomputed per-record RX strings (batch engine); None means
        scan all_records here.
        """
        self._counter += 1
        name = (f"{self.prefix}:{umi}" if umi
                else f"{self.prefix}:{self._counter}").encode()
        b = self._builder
        b.start_unmapped(name, FLAG_UNMAPPED, consensus.bases.tobytes(),
                         consensus.quals)
        b.tag_str(b"RG", self.read_group_id.encode())
        if umi:
            b.tag_str(b"MI", umi.encode())

        cap = lambda x: np.minimum(x, I16_MAX).astype(np.int64)
        total_depths = cap(ss_a.depths) + cap(ss_b.depths)
        total_errors = int(cap(consensus.errors).sum())
        total_bases = int(total_depths.sum())
        rate = (np.float32(total_errors) / np.float32(total_bases)
                if total_bases else np.float32(0))
        b.tag_int(b"cD", int(total_depths.max()) if len(total_depths) else 0)
        b.tag_int(b"cM", int(total_depths.min()) if len(total_depths) else 0)
        b.tag_float(b"cE", float(rate))

        for tag_d, tag_m, tag_e, ss in ((b"aD", b"aM", b"aE", ss_a),
                                        (b"bD", b"bM", b"bE", ss_b)):
            d = cap(ss.depths)
            errs = int(cap(ss.errors).sum())
            total = int(d.sum())
            srate = np.float32(errs) / np.float32(total) if total else np.float32(0)
            b.tag_int(tag_d, int(d.max()) if len(d) else 0)
            b.tag_int(tag_m, int(d.min()) if len(d) else 0)
            b.tag_float(tag_e, float(srate))

        if self.options.produce_per_base_tags:
            b.tag_array_i16(b"ad", cap(ss_a.depths))
            b.tag_array_i16(b"bd", cap(ss_b.depths))
            b.tag_array_i16(b"ae", cap(ss_a.errors))
            b.tag_array_i16(b"be", cap(ss_b.errors))
            b.tag_str(b"ac", ss_a.bases.tobytes())
            b.tag_str(b"bc", ss_b.bases.tobytes())
            b.tag_str(b"aq", (ss_a.quals + 33).astype(np.uint8).tobytes())
            b.tag_str(b"bq", (ss_b.quals + 33).astype(np.uint8).tobytes())

        if self.options.cell_tag:
            ct = self.options.cell_tag.encode()
            for raw in source_raws:
                cb = raw.get_str(ct)
                if cb:
                    b.tag_str(ct, cb.encode())
                    break

        # RX consensus over ALL records in the MI group (rs:1513-1532).
        umis = (rx_umis if rx_umis is not None else
                [u for u in (r.get_str(b"RX") for r in all_records) if u])
        if umis:
            cu = consensus_umis(umis)
            if cu:
                b.tag_str(b"RX", cu.encode())

        self.stats.consensus_reads_generated += 1
        return b.finish()

    def _finish(self, mol, vcr_r1, vcr_r2) -> Optional[bytes]:
        """Geometry + combine + masking after the SS device pass (rs:838-908)."""
        consensus_length = mol["consensus_length"]
        to_ascii = lambda vcr: _SS(
            bases=CODE_TO_BASE[np.minimum(vcr.bases, N_CODE)].copy(),
            quals=np.asarray(vcr.quals, dtype=np.uint8).copy(),
            depths=np.asarray(vcr.depths, dtype=np.int64),
            errors=np.asarray(vcr.errors, dtype=np.int64),
            raw_read_count=0)
        ss_r1, ss_r2 = to_ascii(vcr_r1), to_ascii(vcr_r2)
        ss_r1.raw_read_count = mol["n_r1"]
        ss_r2.raw_read_count = mol["n_r2"]
        n_filtered = mol["n_r1"] + mol["n_r2"]

        if consensus_length < len(ss_r1.bases) or consensus_length < len(ss_r2.bases):
            self.stats.reject("ClipOverlapFailed", n_filtered)
            return None

        r1_neg, r2_neg = mol["r1_is_negative"], mol["r2_is_negative"]
        if r1_neg:
            oriented_r1, oriented_r2 = _rc_ss(ss_r1), ss_r2
        else:
            oriented_r1, oriented_r2 = ss_r1, _rc_ss(ss_r2)
        padded_r1 = _pad_ss(oriented_r1, consensus_length, r1_neg)
        padded_r2 = _pad_ss(oriented_r2, consensus_length, r2_neg)

        try:
            consensus = self._combine(padded_r1, padded_r2)
        except DuplexDisagreementError:
            self.stats.reject("HighDuplexDisagreement", n_filtered)
            self.stats.consensus_reads_rejected_hdd += 1
            raise
        consensus = self._mask_quals(consensus, padded_r1, padded_r2)
        if r1_neg:
            consensus = _rc_ss(consensus)
            ss_for_ac, ss_for_bc = _rc_ss(padded_r1), _rc_ss(padded_r2)
        else:
            ss_for_ac, ss_for_bc = padded_r1, padded_r2

        return self._build_record(consensus, ss_for_ac, ss_for_bc, mol["umi"],
                                  mol["source_raws"], mol["records"],
                                  rx_umis=mol.get("rx_umis"))

    # ------------------------------------------------------------ driver

    def call_groups(self, groups) -> list:
        """Process [(mi, [RawRecord])] -> consensus record bytes (batched).

        All molecules' SS jobs run as one device pass. Rejected groups
        (including recoverable duplex-disagreement drops) go to
        self.rejected_reads when track_rejects is on.
        """
        molecules = []
        for mi, records in groups:
            mol = self.prepare(records, umi=mi)
            if mol is None:
                if self.track_rejects:
                    self.rejected_reads.extend(records)
                continue
            molecules.append(mol)
        if not molecules:
            return []
        jobs = []
        for mol in molecules:
            jobs.extend([mol["job_r1"], mol["job_r2"]])
        results = self.ss._run_jobs(jobs)
        out = []
        for i, mol in enumerate(molecules):
            vcr_r1 = self.ss.result_to_consensus_read(mol["job_r1"], results[2 * i])
            vcr_r2 = self.ss.result_to_consensus_read(mol["job_r2"], results[2 * i + 1])
            try:
                rec = self._finish(mol, vcr_r1, vcr_r2)
            except DuplexDisagreementError:
                rec = None
            if rec is not None:
                out.append(rec)
            elif self.track_rejects:
                self.rejected_reads.extend(mol["records"])
        return out
