"""Consensus read filtering: per-read thresholds and per-base masking.

Behavioral parity with the reference's consensus filter library
(/root/reference/crates/fgumi-consensus/src/filter.rs):

- ``FilterThresholds`` {min_reads, max_read_error_rate, max_base_error_rate}
  with 1->3 expansion filling missing values from the last (filter.rs:20-27).
- Read-level: cD/cE tags checked against the CC tier; duplex additionally
  checks per-metric best values against the stricter AB tier and worst values
  against the lenient BA tier (filter.rs:503-616).
- Base-level: masks to N @ Q2 when below min quality / min depth / above the
  per-base error rate; duplex combines ad/bd + ae/be and optionally requires
  single-strand agreement of ac/bc (filter.rs:745-905).
- Mean quality is computed over the FULL read length prior to masking
  (filter.rs:668-696); no-call check runs after masking.

Methylation (cu/ct depth, strand-agreement, conversion-fraction) filters are
not yet implemented (the methylation subsystem lands separately).
"""

from dataclasses import dataclass

import numpy as np

from ..constants import MIN_PHRED
from ..io.bam import FLAG_SECONDARY, FLAG_SUPPLEMENTARY, RawRecord

# BAM nibble code for N in packed sequence.
_N_NIBBLE = 15

PASS = "pass"
INSUFFICIENT_READS = "insufficient_reads"
EXCESSIVE_ERROR_RATE = "excessive_error_rate"
LOW_QUALITY = "low_quality"
TOO_MANY_NO_CALLS = "too_many_no_calls"


def expand_three_from_last(values):
    """Expand a 1-3 element sequence to exactly 3, filling from the last."""
    if not values:
        raise ValueError("at least one value required")
    v = list(values[:3])
    while len(v) < 3:
        v.append(v[-1])
    return v


@dataclass(frozen=True)
class FilterThresholds:
    min_reads: int
    max_read_error_rate: float
    max_base_error_rate: float


@dataclass(frozen=True)
class FilterConfig:
    cc: FilterThresholds  # final (duplex) consensus tier
    ab: FilterThresholds  # stricter single-strand tier
    ba: FilterThresholds  # lenient single-strand tier
    single_strand: FilterThresholds
    min_base_quality: int | None
    min_mean_base_quality: float | None
    max_no_call_fraction: float
    require_ss_agreement: bool = False
    # EM-Seq/TAPS filters (filter.rs 905-1320); see the module tail
    methylation_depth: object = None  # MethylationDepthThresholds | None
    require_strand_methylation_agreement: bool = False
    min_conversion_fraction: float | None = None
    methylation_mode: str | None = None  # "em-seq" | "taps"

    @classmethod
    def new(cls, min_reads, max_read_error_rate, max_base_error_rate,
            min_base_quality=None, min_mean_base_quality=None,
            max_no_call_fraction=0.2, require_ss_agreement=False,
            methylation_depth=None,
            require_strand_methylation_agreement=False,
            min_conversion_fraction=None, methylation_mode=None):
        """Build from 1-3-valued options, validating tier ordering
        (filter.rs:237-330: depths high->low CC>=AB>=BA; error rates AB<=BA)."""
        mr = expand_three_from_last(min_reads)
        re_ = expand_three_from_last(max_read_error_rate or [1.0])
        be = expand_three_from_last(max_base_error_rate or [1.0])
        if mr[1] > mr[0]:
            raise ValueError(
                f"min-reads values must be specified high to low: "
                f"AB ({mr[1]}) > CC ({mr[0]})")
        if mr[2] > mr[1]:
            raise ValueError(
                f"min-reads values must be specified high to low: "
                f"BA ({mr[2]}) > AB ({mr[1]})")
        if re_[1] > re_[2]:
            raise ValueError(
                f"max-read-error-rate for AB ({re_[1]}) must be <= BA ({re_[2]})")
        if be[1] > be[2]:
            raise ValueError(
                f"max-base-error-rate for AB ({be[1]}) must be <= BA ({be[2]})")
        return cls(
            cc=FilterThresholds(mr[0], re_[0], be[0]),
            ab=FilterThresholds(mr[1], re_[1], be[1]),
            ba=FilterThresholds(mr[2], re_[2], be[2]),
            single_strand=FilterThresholds(min_reads[0],
                                           max_read_error_rate[0]
                                           if max_read_error_rate else 1.0,
                                           max_base_error_rate[0]
                                           if max_base_error_rate else 1.0),
            min_base_quality=min_base_quality,
            min_mean_base_quality=min_mean_base_quality,
            max_no_call_fraction=max_no_call_fraction,
            require_ss_agreement=require_ss_agreement,
            methylation_depth=(MethylationDepthThresholds.from_values(
                methylation_depth) if methylation_depth else None),
            require_strand_methylation_agreement=(
                require_strand_methylation_agreement),
            min_conversion_fraction=min_conversion_fraction,
            methylation_mode=methylation_mode)


def is_duplex_consensus(rec: RawRecord) -> bool:
    """A duplex consensus read carries both aD and bD tags (filter.rs:493-497)."""
    return rec.find_tag(b"aD") is not None and rec.find_tag(b"bD") is not None


def filter_read(rec: RawRecord, t: FilterThresholds) -> str:
    """Per-read check against cD depth / cE error rate (filter.rs:503-531)."""
    depth = rec.get_int(b"cD")
    got_ce = rec.find_tag(b"cE")
    error_rate = got_ce[1] if got_ce and got_ce[0] == "f" else None
    if depth is None or error_rate is None:
        raise ValueError(
            "read does not appear to have consensus calling tags (cD/cE) "
            "present; filter requires reads produced by consensus calling")
    if depth < t.min_reads:
        return INSUFFICIENT_READS
    if float(error_rate) > t.max_read_error_rate:
        return EXCESSIVE_ERROR_RATE
    return PASS


def filter_duplex_read(rec: RawRecord, cc: FilterThresholds,
                       ab: FilterThresholds, ba: FilterThresholds) -> str:
    """CC tier, then per-metric best vs AB tier and worst vs BA tier
    (filter.rs:538-616). best/worst are per-metric extremes across strands,
    not the biological AB/BA values."""
    result = filter_read(rec, cc)
    if result != PASS:
        return result
    ab_depth = rec.get_int(b"aD")
    if ab_depth is None:
        ab_depth = rec.get_int(b"aM")
    ba_depth = rec.get_int(b"bD")
    if ba_depth is None:
        ba_depth = rec.get_int(b"bM")
    got = rec.find_tag(b"aE")
    ab_err = got[1] if got and got[0] == "f" else None
    got = rec.find_tag(b"bE")
    ba_err = got[1] if got and got[0] == "f" else None

    if ab_depth is None and ba_depth is None:
        return PASS
    depths = sorted(d for d in (ab_depth, ba_depth) if d is not None)
    if len(depths) == 2:
        worst_depth, best_depth = depths
    else:
        worst_depth, best_depth = 0, depths[0]
    errs = [e for e in (ab_err, ba_err) if e is not None]
    if len(errs) == 2:
        best_err, worst_err = min(errs), max(errs)
    elif errs:
        best_err = worst_err = errs[0]
    else:
        best_err = worst_err = 0.0

    if best_depth < ab.min_reads:
        return INSUFFICIENT_READS
    if float(best_err) > ab.max_read_error_rate:
        return EXCESSIVE_ERROR_RATE
    if worst_depth < ba.min_reads:
        return INSUFFICIENT_READS
    if float(worst_err) > ba.max_read_error_rate:
        return EXCESSIVE_ERROR_RATE
    return PASS


def _seq_qual_view(buf):
    """(seq_offset, qual_offset, l_seq) for a record's wire bytes."""
    l_read_name = buf[8]
    n_cigar = int.from_bytes(buf[12:14], "little")
    l_seq = int.from_bytes(buf[16:20], "little")
    seq_off = 32 + l_read_name + 4 * n_cigar
    qual_off = seq_off + (l_seq + 1) // 2
    return seq_off, qual_off, l_seq


def _unpack_nibbles(buf, seq_off, l_seq) -> np.ndarray:
    packed = np.frombuffer(buf, dtype=np.uint8, count=(l_seq + 1) // 2,
                           offset=seq_off)
    nib = np.empty(2 * len(packed), dtype=np.uint8)
    nib[0::2] = packed >> 4
    nib[1::2] = packed & 0xF
    return nib[:l_seq]


def _write_nibbles(buf, seq_off, nib):
    n = len(nib)
    if n % 2:
        nib = np.append(nib, 0)
    buf[seq_off:seq_off + (n + 1) // 2] = ((nib[0::2] << 4)
                                           | nib[1::2]).astype(np.uint8).tobytes()


def _per_base_padded(rec: RawRecord, tag: bytes, l_seq: int):
    """B-array tag as float64 padded/truncated to l_seq with zeros, or None."""
    got = rec.find_tag(tag)
    if got is None or got[0] != "B":
        return None
    arr = np.asarray(got[1], dtype=np.float64)[:l_seq]
    if len(arr) < l_seq:
        arr = np.pad(arr, (0, l_seq - len(arr)))
    return arr


def _string_or_u8_array(rec: RawRecord, tag: bytes):
    """Tag value as raw bytes from either a Z string or a B:C/B:c array
    (filter.rs:716-733 find_string_or_uint8_array)."""
    got = rec.find_tag(tag)
    if got is None:
        return None
    typ, val = got
    if typ == "Z":
        return val.encode()
    if typ == "B" and isinstance(val, np.ndarray) and val.dtype.itemsize == 1:
        return val.astype(np.uint8).tobytes()
    return None


def mean_base_quality_full_length(buf) -> float:
    """Sum of all quals / full read length, incl. N bases (filter.rs:668-696)."""
    _, qual_off, l_seq = _seq_qual_view(buf)
    if l_seq == 0:
        return 0.0
    quals = np.frombuffer(buf, dtype=np.uint8, count=l_seq, offset=qual_off)
    return float(quals.sum()) / l_seq


def count_no_calls(buf) -> int:
    seq_off, _, l_seq = _seq_qual_view(buf)
    return int((_unpack_nibbles(buf, seq_off, l_seq) == _N_NIBBLE).sum())


def mask_bases(buf: bytearray, t: FilterThresholds,
               min_base_quality: int | None, rec: RawRecord = None) -> int:
    """Mask simplex consensus bases in place; returns newly-masked count.

    Per-base depth/error masking applies only when BOTH cd and ce are present
    (filter.rs:790-794); otherwise only the quality mask applies. Vectorized
    over the read (no per-base interpreter loop). `rec` may carry the
    caller's already-parsed view of the same bytes (tag index reuse); the
    mutation below touches only seq/qual, never the aux region it indexes.
    """
    if rec is None:
        rec = RawRecord(bytes(buf))
    seq_off, qual_off, l_seq = _seq_qual_view(buf)
    if l_seq == 0:
        return 0
    cd = _per_base_padded(rec, b"cd", l_seq)
    ce = _per_base_padded(rec, b"ce", l_seq)
    quals = np.frombuffer(buf, dtype=np.uint8, count=l_seq, offset=qual_off)
    mask = np.zeros(l_seq, dtype=bool)
    if min_base_quality is not None:
        mask |= quals < min_base_quality
    if cd is not None and ce is not None:
        mask |= cd < t.min_reads
        with np.errstate(divide="ignore", invalid="ignore"):
            rate = np.where(cd > 0, ce / np.maximum(cd, 1), 0.0)
        mask |= (cd > 0) & (rate > t.max_base_error_rate)
    if not mask.any():
        return 0
    nib = _unpack_nibbles(buf, seq_off, l_seq).copy()
    masked = int((mask & (nib != _N_NIBBLE)).sum())
    nib[mask] = _N_NIBBLE
    _write_nibbles(buf, seq_off, nib)
    new_quals = quals.copy()
    new_quals[mask] = MIN_PHRED
    buf[qual_off:qual_off + l_seq] = new_quals.tobytes()
    return masked


def mask_duplex_bases(buf: bytearray, cc: FilterThresholds,
                      ab: FilterThresholds, ba: FilterThresholds,
                      min_base_quality: int | None,
                      require_ss_agreement: bool,
                      rec: RawRecord = None) -> int:
    """Mask duplex consensus bases in place; returns newly-masked count
    (filter.rs:804-905). Already-N bases are skipped entirely (neither
    re-masked nor re-counted, and their quals are left untouched)."""
    if rec is None:
        rec = RawRecord(bytes(buf))
    seq_off, qual_off, l_seq = _seq_qual_view(buf)
    if l_seq == 0:
        return 0
    ad = _per_base_padded(rec, b"ad", l_seq)
    ae = _per_base_padded(rec, b"ae", l_seq)
    bd = _per_base_padded(rec, b"bd", l_seq)
    be = _per_base_padded(rec, b"be", l_seq)
    zeros = np.zeros(l_seq, dtype=np.float64)
    ab_depth = ad if ad is not None else zeros
    ba_depth = bd if bd is not None else zeros
    ab_errors = ae if ae is not None else zeros
    ba_errors = be if be is not None else zeros

    best_depth = np.maximum(ab_depth, ba_depth)
    worst_depth = np.minimum(ab_depth, ba_depth)
    ab_rate = np.where(ab_depth > 0, ab_errors / np.maximum(ab_depth, 1), 0.0)
    ba_rate = np.where(ba_depth > 0, ba_errors / np.maximum(ba_depth, 1), 0.0)
    best_rate = np.minimum(ab_rate, ba_rate)
    worst_rate = np.maximum(ab_rate, ba_rate)
    total_depth = ab_depth + ba_depth
    total_rate = np.where(total_depth > 0,
                          (ab_errors + ba_errors) / np.maximum(total_depth, 1),
                          0.0)
    quals = np.frombuffer(buf, dtype=np.uint8, count=l_seq, offset=qual_off)

    mask = (total_depth < cc.min_reads) | (total_rate > cc.max_base_error_rate)
    mask |= (best_depth < ab.min_reads) | (best_rate > ab.max_base_error_rate)
    mask |= (worst_depth < ba.min_reads) | (worst_rate > ba.max_base_error_rate)
    if min_base_quality is not None:
        mask |= quals < min_base_quality
    if require_ss_agreement:
        # ac/bc may be Z strings or B:C arrays; missing/short -> N
        a_bases = np.full(l_seq, ord("N"), dtype=np.uint8)
        b_bases = np.full(l_seq, ord("N"), dtype=np.uint8)
        ac = _string_or_u8_array(rec, b"ac")
        bc = _string_or_u8_array(rec, b"bc")
        if ac:
            n = min(len(ac), l_seq)
            a_bases[:n] = np.frombuffer(ac[:n], dtype=np.uint8)
        if bc:
            n = min(len(bc), l_seq)
            b_bases[:n] = np.frombuffer(bc[:n], dtype=np.uint8)
        mask |= (ab_depth > 0) & (ba_depth > 0) & (a_bases != b_bases)

    nib = _unpack_nibbles(buf, seq_off, l_seq).copy()
    mask &= nib != _N_NIBBLE  # skip already-N positions
    if not mask.any():
        return 0
    masked = int(mask.sum())
    nib[mask] = _N_NIBBLE
    _write_nibbles(buf, seq_off, nib)
    new_quals = quals.copy()
    new_quals[mask] = MIN_PHRED
    buf[qual_off:qual_off + l_seq] = new_quals.tobytes()
    return masked


def no_call_check(buf, max_no_call_fraction: float) -> str:
    """< 1.0 means fraction of read length; >= 1.0 means absolute count
    (commands/filter.rs:150-155)."""
    _, _, l_seq = _seq_qual_view(buf)
    n = count_no_calls(buf)
    if max_no_call_fraction < 1.0:
        if l_seq and n / l_seq > max_no_call_fraction:
            return TOO_MANY_NO_CALLS
    elif n > max_no_call_fraction:
        return TOO_MANY_NO_CALLS
    return PASS


# ---------------------------------------------------------------------------
# Array-level threshold core — the one copy of the filter's numeric
# decisions, shared by the batch host engine (commands/fast_filter.py) and
# the device-resident fused filter stage (consensus/device_filter.py +
# ops/kernel.py). The per-record functions above stay the semantic
# reference; these are their vectorized twins over (n,) / (n, L) arrays.
# ---------------------------------------------------------------------------

#: integer verdict codes for the array paths (order matters only for the
#: mapping below; the precedence is encoded in simplex_read_verdicts).
R_PASS, R_INSUFFICIENT, R_ERROR_RATE, R_LOW_QUALITY, R_NO_CALLS = range(5)
RESULT_NAMES = {R_PASS: PASS, R_INSUFFICIENT: INSUFFICIENT_READS,
                R_ERROR_RATE: EXCESSIVE_ERROR_RATE,
                R_LOW_QUALITY: LOW_QUALITY, R_NO_CALLS: TOO_MANY_NO_CALLS}


def simplex_read_verdicts(cD, cE, qual_sum, n_after, l_seq,
                          t: FilterThresholds,
                          min_mean_base_quality, max_no_call_fraction):
    """Per-read verdict codes for simplex consensus reads, from the scalar
    per-read reductions: cD (max per-base depth, i16-clamped), cE (the
    float32 error-rate tag value), qual_sum (sum of the PRE-mask quals over
    the full read), n_after (N count AFTER base masking), l_seq.

    Exactly filter_read -> mean-quality check -> no_call_check, in the
    fast-filter precedence (error rate set first, then depth outranks it;
    later checks apply only to still-passing reads)."""
    n = len(cD)
    res = np.full(n, R_PASS, dtype=np.int8)
    res[np.asarray(cE, dtype=np.float64) > t.max_read_error_rate] = \
        R_ERROR_RATE
    res[cD < t.min_reads] = R_INSUFFICIENT
    l_seq = np.asarray(l_seq, dtype=np.int64)
    if min_mean_base_quality is not None:
        mean = np.where(l_seq > 0,
                        np.asarray(qual_sum, np.float64)
                        / np.maximum(l_seq, 1), 0.0)
        res[(res == R_PASS) & (mean < min_mean_base_quality)] = R_LOW_QUALITY
    if max_no_call_fraction < 1.0:
        frac = np.where(l_seq > 0,
                        np.asarray(n_after, np.float64)
                        / np.maximum(l_seq, 1), 0.0)
        too_many = (l_seq > 0) & (frac > max_no_call_fraction)
    else:
        too_many = np.asarray(n_after) > max_no_call_fraction
    res[(res == R_PASS) & too_many] = R_NO_CALLS
    return res


def simplex_base_mask_arrays(cd, ce, quals, in_len, t: FilterThresholds,
                             min_base_quality, has_per_base=None):
    """(n, L) boolean mask twin of mask_bases: quality mask everywhere,
    depth/error masks only on rows that carry per-base evidence
    (``has_per_base``; None = all rows). All terms honor ``in_len``."""
    mask = np.zeros(in_len.shape, dtype=bool)
    if min_base_quality is not None:
        mask |= (quals < min_base_quality) & in_len
    pb = in_len if has_per_base is None else has_per_base[:, None] & in_len
    mask |= pb & (cd < t.min_reads)
    with np.errstate(divide="ignore", invalid="ignore"):
        rate = np.where(cd > 0, ce / np.maximum(cd, 1), 0.0)
    mask |= pb & (cd > 0) & (rate > t.max_base_error_rate)
    return mask


def duplex_base_mask_arrays(ad, ae, bd, be, cc: FilterThresholds,
                            ab: FilterThresholds, ba: FilterThresholds):
    """(n, L) boolean mask twin of mask_duplex_bases' depth/error terms
    (quality and ss-agreement terms are composed by the caller)."""
    best_depth = np.maximum(ad, bd)
    worst_depth = np.minimum(ad, bd)
    with np.errstate(divide="ignore", invalid="ignore"):
        ab_rate = np.where(ad > 0, ae / np.maximum(ad, 1), 0.0)
        ba_rate = np.where(bd > 0, be / np.maximum(bd, 1), 0.0)
    best_rate = np.minimum(ab_rate, ba_rate)
    worst_rate = np.maximum(ab_rate, ba_rate)
    total_depth = ad + bd
    with np.errstate(divide="ignore", invalid="ignore"):
        total_rate = np.where(total_depth > 0,
                              (ae + be) / np.maximum(total_depth, 1), 0.0)
    mask = (total_depth < cc.min_reads) | (total_rate > cc.max_base_error_rate)
    mask |= (best_depth < ab.min_reads) | (best_rate > ab.max_base_error_rate)
    mask |= (worst_depth < ba.min_reads) | (worst_rate > ba.max_base_error_rate)
    return mask


def base_error_rate_table(max_rate: float, size: int = 32768) -> np.ndarray:
    """Exact integer reformulation of the per-base error-rate mask for the
    device kernel: ``table[c]`` is the smallest integer error count ``e``
    with ``float64(e) / float64(c) > max_rate`` — so the device's pure
    integer compare ``(cd > 0) & (ce >= table[cd])`` reproduces the host's
    f64 division bit-for-bit without any floating point on the device
    (f64 division is monotone in the numerator, so the threshold integer is
    well-defined). ``table[0]`` is ``size`` (the cd > 0 gate makes it
    unreachable); entries are clamped to ``size`` (= "never masks")."""
    c = np.arange(size, dtype=np.float64)
    guess = np.floor(max_rate * c).astype(np.int64)
    table = np.full(size, size, dtype=np.int64)
    with np.errstate(divide="ignore", invalid="ignore"):
        # f64 division is monotone in e: test the 5 candidates around the
        # float guess, keep the smallest that satisfies the comparison
        for delta in (3, 2, 1, 0, -1):
            e = np.maximum(guess + delta, 0)
            ok = e / np.maximum(c, 1) > max_rate
            table = np.where(ok & (e < table), e, table)
    table[0] = size
    return np.minimum(table, size).astype(np.int32)


def template_passes(records, pass_flags) -> bool:
    """All primary records must pass; a template with no primaries fails
    (filter.rs:371-395)."""
    has_primary = False
    for rec, ok in zip(records, pass_flags):
        if rec.flag & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY):
            continue
        has_primary = True
        if not ok:
            return False
    return has_primary


# ---------------------------------------------------------------------------
# Methylation (EM-Seq/TAPS) filters — filter.rs (fgumi-consensus) 905-1320
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class MethylationDepthThresholds:
    """1-3 values [duplex(cu+ct), AB(au+at), BA(bu+bt)], missing filled from
    the last (MethylationDepthThresholds::from_values)."""

    duplex: int
    ab: int
    ba: int

    @classmethod
    def from_values(cls, values):
        d, a, b = expand_three_from_last([int(v) for v in values])
        return cls(d, a, b)


def _mask_positions(buf: bytearray, mask: np.ndarray) -> int:
    """Apply a boolean mask to seq/qual in place; returns newly-masked
    count — the shared tail of the methylation masking passes. Already-N
    positions are skipped ENTIRELY (seq and qual untouched), matching the
    reference's is_base_n continue (filter.rs:969,1024,1213) and the fast
    engine's skip-N duplex masking."""
    if not mask.any():
        return 0
    seq_off, qual_off, l_seq = _seq_qual_view(buf)
    nib = _unpack_nibbles(buf, seq_off, l_seq).copy()
    mask = mask & (nib != _N_NIBBLE)
    if not mask.any():
        return 0
    masked = int(mask.sum())
    nib[mask] = _N_NIBBLE
    _write_nibbles(buf, seq_off, nib)
    quals = np.frombuffer(buf, dtype=np.uint8, count=l_seq,
                          offset=qual_off).copy()
    quals[mask] = MIN_PHRED
    buf[qual_off:qual_off + l_seq] = quals.tobytes()
    return masked


def mask_methylation_depth(buf: bytearray, rec: RawRecord,
                           thresholds: MethylationDepthThresholds,
                           duplex: bool) -> int:
    """Mask bases whose methylation evidence depth is too low
    (mask_methylation_depth_{simplex,duplex}_raw_with_tags): simplex checks
    cu+ct against the first threshold; duplex additionally checks au+at and
    bu+bt. No cu/ct tags at all -> no-op. Returns newly-masked count."""
    _, _, l_seq = _seq_qual_view(buf)
    if l_seq == 0:
        return 0
    cu = _per_base_padded(rec, b"cu", l_seq)
    ct = _per_base_padded(rec, b"ct", l_seq)
    if cu is None and ct is None:
        return 0
    z = np.zeros(l_seq)
    total = (cu if cu is not None else z) + (ct if ct is not None else z)
    mask = total < thresholds.duplex
    if duplex:
        for u_tag, t_tag, thr in ((b"au", b"at", thresholds.ab),
                                  (b"bu", b"bt", thresholds.ba)):
            u = _per_base_padded(rec, u_tag, l_seq)
            t = _per_base_padded(rec, t_tag, l_seq)
            mask |= ((u if u is not None else z)
                     + (t if t is not None else z)) < thr
    return _mask_positions(buf, mask)


def resolve_ref_codes(rec: RawRecord, reference, ref_names):
    """Per-query-position UPPERCASE reference byte as int32 (-1 for
    insertions/soft-clips), or None for unmapped/unresolvable records
    (resolve_ref_bases_for_record; shared walker in methylation.py)."""
    from ..io.bam import FLAG_UNMAPPED
    from .methylation import ref_bytes_for_alignment

    if rec.flag & FLAG_UNMAPPED or rec.ref_id < 0 \
            or rec.ref_id >= len(ref_names):
        return None
    ref_seq = reference.get(ref_names[rec.ref_id]) \
        if hasattr(reference, "get") else None
    if ref_seq is None:
        return None
    _, _, l_seq = _seq_qual_view(rec.data)
    return ref_bytes_for_alignment(rec.cigar(), rec.pos, ref_seq, l_seq)


def mask_strand_methylation_agreement(buf: bytearray, rec: RawRecord,
                                      ref_codes) -> int:
    """Mask BOTH positions of a CpG dinucleotide when the top strand's
    methylation call (au/at at the C) disagrees with the bottom strand's
    (bu/bt at the G); majority rule unconverted>converted, positions with
    no evidence on either strand are skipped
    (mask_strand_methylation_agreement_raw_with_ref_bases_and_tags)."""
    if ref_codes is None:
        return 0
    _, _, l_seq = _seq_qual_view(buf)
    au = _per_base_padded(rec, b"au", l_seq)
    at = _per_base_padded(rec, b"at", l_seq)
    bu = _per_base_padded(rec, b"bu", l_seq)
    bt = _per_base_padded(rec, b"bt", l_seq)
    if au is None and bu is None:
        return 0
    z = np.zeros(l_seq)
    au = au if au is not None else z
    at = at if at is not None else z
    bu = bu if bu is not None else z
    bt = bt if bt is not None else z
    mask = np.zeros(l_seq, dtype=bool)
    for i in range(l_seq - 1):
        if ref_codes[i] != ord("C") or ref_codes[i + 1] != ord("G"):
            continue
        top_total = au[i] + at[i]
        bot_total = bu[i + 1] + bt[i + 1]
        if top_total == 0 or bot_total == 0:
            continue
        if (au[i] > at[i]) != (bu[i + 1] > bt[i + 1]):
            mask[i] = True
            mask[i + 1] = True
    return _mask_positions(buf, mask)


def check_conversion_fraction(rec: RawRecord, min_fraction: float,
                              ref_codes, mode: str) -> bool:
    """Read-level conversion check over non-CpG ref-C positions with cu/ct
    evidence: EM-Seq requires converted/total >= threshold (complete
    conversion = good library), TAPS unconverted/total (non-CpG Cs should
    stay; check_conversion_fraction_raw_with_ref_bases_and_tags). Records
    without tags / reference mapping pass."""
    if not mode or ref_codes is None:
        return True
    _, _, l_seq = _seq_qual_view(rec.data)
    cu = _per_base_padded(rec, b"cu", l_seq)
    ct = _per_base_padded(rec, b"ct", l_seq)
    if cu is None and ct is None:
        return True
    z = np.zeros(l_seq)
    cu = cu if cu is not None else z
    ct = ct if ct is not None else z
    num = 0.0
    tot = 0.0
    for i in range(l_seq):
        if ref_codes[i] != ord("C"):
            continue
        if i + 1 < l_seq and ref_codes[i + 1] == ord("G"):
            continue  # CpG sites are where real methylation lives — skip
        ev = cu[i] + ct[i]
        if ev > 0:
            num += cu[i] if mode == "taps" else ct[i]
            tot += ev
    if tot == 0:
        return True
    return num / tot >= min_fraction
