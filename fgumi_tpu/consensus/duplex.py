"""Duplex consensus caller (two-stage: single-strand then strand combination).

Mirrors /root/reference/crates/fgumi-consensus/src/duplex_caller.rs:
- reads grouped by base MI with /A (AB strand) and /B (BA strand) suffixes
  (duplex_caller.rs:477-527);
- min_reads = [total, XY, YX] with padTo(3, last) and high-to-low validation
  (duplex_caller.rs:361-400);
- SS consensus via the vanilla caller with min_reads=1 / min_consensus_qual=Q2
  (duplex_caller.rs:400-420), X/Y alignment filtering across strands
  (duplex_caller.rs:1871-1933), strand-orientation validation (1830-1860);
- stage-2 combine (duplex_consensus, 844-1021): truncate to min length, agreement
  sums quality (cap Q93), disagreement takes the higher-quality base with the
  difference, equal-disagreement and N propagate (N, Q2); exact per-base errors
  counted against source reads;
- output tags MI, RG, aD/aE/aM [+ac/ad/ae/aq], bD/bE/bM [+bc/bd/be/bq], cD/cE/cM,
  RX (strand-reoriented UMI consensus) (duplex_read_into, 1056-1249).

Stage 1 (the hot loop) executes on the batched TPU kernel via the shared vanilla
job machinery; stage 2 is cheap vectorized host math.
"""

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..constants import MAX_PHRED, MIN_PHRED, N_CODE
from ..core.overlap import num_bases_extending_past_mate
from ..io.bam import (FLAG_FIRST, FLAG_LAST, FLAG_PAIRED, FLAG_REVERSE,
                      RawRecord, RecordBuilder)
from .simple_umi import consensus_umis
from .rejects import RejectTracking
from .vanilla import (CallerStats, I16_MAX, R1, R2, VanillaConsensusCaller,
                      VanillaConsensusRead, VanillaOptions, _TYPE_FLAGS)


@dataclass
class DuplexConsensusRead:
    """Stage-2 result (DuplexConsensusRead, duplex_caller.rs:225-256)."""

    id: str
    bases: np.ndarray
    quals: np.ndarray
    errors: np.ndarray
    ab_consensus: VanillaConsensusRead
    ba_consensus: Optional[VanillaConsensusRead]
    is_ba_only: bool = False
    methylation: object = None  # combined MethylationAnnotation when enabled


def parse_min_reads(values) -> tuple:
    """[total] / [total, ss] / [total, xy, yx] -> (total, xy, yx); high-to-low
    validation (duplex_caller.rs:374-400)."""
    values = list(values)
    if not values or len(values) > 3:
        raise ValueError("min_reads must have 1-3 values: total, [XY, [YX]]")
    last = values[-1]
    total = values[0]
    xy = values[1] if len(values) > 1 else last
    yx = values[2] if len(values) > 2 else last
    if xy > total or yx > xy:
        raise ValueError("min-reads values must be specified high to low (total >= XY >= YX)")
    return total, xy, yx


def split_mi(mi: str):
    """MI -> (base, strand) where strand is 'A'/'B'; raises without suffix."""
    if mi.endswith("/A"):
        return mi[:-2], "A"
    if mi.endswith("/B"):
        return mi[:-2], "B"
    raise ValueError(
        f"Read has MI tag {mi!r} without /A or /B suffix. Duplex consensus requires "
        "input from `group --strategy paired`, which marks the source strand.")


def duplex_combine(ab: Optional[VanillaConsensusRead], ba: Optional[VanillaConsensusRead],
                   source_reads=None) -> Optional[DuplexConsensusRead]:
    """Stage-2 combination (duplex_consensus, duplex_caller.rs:844-1021), vectorized."""
    length = min(len(ab.bases) if ab is not None else np.inf,
                 len(ba.bases) if ba is not None else np.inf)
    length = int(length)
    if ab is not None and not (ab.depths[:length] > 0).any():
        ab = None
    if ba is not None and not (ba.depths[:length] > 0).any():
        ba = None

    def strand_ann(c):
        return c.methylation[0] if c is not None and c.methylation else None

    if ab is None and ba is None:
        return None
    if ba is None:
        return DuplexConsensusRead(id=ab.id, bases=ab.bases, quals=ab.quals,
                                   errors=ab.errors, ab_consensus=ab, ba_consensus=None,
                                   methylation=strand_ann(ab))
    if ab is None:
        return DuplexConsensusRead(id=ba.id, bases=ba.bases, quals=ba.quals,
                                   errors=ba.errors, ab_consensus=ba, ba_consensus=None,
                                   is_ba_only=True, methylation=strand_ann(ba))

    a_b = ab.bases[:length].astype(np.int32)
    b_b = ba.bases[:length].astype(np.int32)
    a_q = ab.quals[:length].astype(np.int32)
    b_q = ba.quals[:length].astype(np.int32)

    agree = a_b == b_b
    a_wins = (~agree) & (a_q > b_q)
    b_wins = (~agree) & (b_q > a_q)
    tie = (~agree) & (a_q == b_q)

    # EM-Seq/TAPS conversion artifact (duplex_caller.rs:897-925): a C/T (or
    # G/A) cross-strand pair at a ref-C position is expected conversion, not
    # a disagreement — call the unconverted base with summed quality and no
    # error contribution
    is_conv = np.zeros(length, dtype=bool)
    unconv = np.zeros(length, dtype=np.int32)
    if ab.methylation is not None or ba.methylation is not None:
        from .methylation import A as _A, C as _C, G as _G, T as _T

        is_ref_c = np.zeros(length, dtype=bool)
        for strand in (ab, ba):
            if strand.methylation is not None:
                ann = strand.methylation[0]
                n = min(length, len(ann.is_ref_c))
                is_ref_c[:n] |= ann.is_ref_c[:n]
        ct_pair = ((a_b == _C) & (b_b == _T)) | ((a_b == _T) & (b_b == _C))
        ga_pair = ((a_b == _G) & (b_b == _A)) | ((a_b == _A) & (b_b == _G))
        is_conv = (~agree) & is_ref_c & (ct_pair | ga_pair)
        unconv = np.where(ct_pair, _C, _G).astype(np.int32)

    raw_base = np.where(is_conv, unconv,
                        np.where(agree | a_wins, a_b, b_b))
    raw_qual = np.where(
        (agree | is_conv), np.clip(a_q + b_q, MIN_PHRED, MAX_PHRED),
        np.where(a_wins, np.clip(a_q - b_q, MIN_PHRED, MAX_PHRED),
                 np.where(b_wins, np.clip(b_q - a_q, MIN_PHRED, MAX_PHRED), MIN_PHRED)))

    either_n = (a_b == N_CODE) | (b_b == N_CODE)
    mask = either_n | (raw_qual == MIN_PHRED) | (tie & ~is_conv)
    bases = np.where(mask, N_CODE, raw_base).astype(np.uint8)
    quals = np.where(mask, MIN_PHRED, raw_qual).astype(np.uint8)

    if source_reads:
        # exact errors: disagreements of each source read base with the raw duplex base
        errors = np.zeros(length, dtype=np.int64)
        for sr in source_reads:
            n = min(len(sr.codes), length)
            src = sr.codes[:n].astype(np.int32)
            err = (src != N_CODE) & (raw_base[:n] != N_CODE) & (src != raw_base[:n])
            errors[:n] += err
        errors = np.minimum(errors, I16_MAX)
    else:
        # approximate from per-strand counts (duplex_caller.rs:958-972)
        a_e = ab.errors[:length]
        b_e = ba.errors[:length]
        a_d = ab.depths[:length]
        b_d = ba.depths[:length]
        errors = np.where(agree, a_e + b_e,
                          np.where(raw_base == a_b, a_e + (b_d - b_e),
                                   b_e + (a_d - a_e)))
        errors = np.minimum(errors, I16_MAX)
    # conversion artifacts count as agreement: no errors (rs:948-951)
    if is_conv.any():
        errors = np.where(is_conv, 0, errors)

    def truncate(c):
        meth = c.methylation
        if meth is not None:
            meth = (meth[0].truncate(length), meth[1])
        return VanillaConsensusRead(
            id=c.id, bases=c.bases[:length], quals=c.quals[:length],
            depths=c.depths[:length], errors=c.errors[:length],
            methylation=meth)

    combined = None
    if ab.methylation is not None or ba.methylation is not None:
        from . import methylation as meth_mod

        combined = meth_mod.combine_annotations(strand_ann(ab), strand_ann(ba),
                                                length)
    return DuplexConsensusRead(id=ab.id, bases=bases, quals=quals, errors=errors,
                               ab_consensus=truncate(ab), ba_consensus=truncate(ba),
                               methylation=combined)


class DuplexConsensusCaller(RejectTracking):
    """Duplex caller over base-MI groups carrying /A and /B strand reads."""

    def __init__(self, read_name_prefix: str, read_group_id: str, min_reads=(1,),
                 min_input_base_quality: int = 10, produce_per_base_tags: bool = True,
                 trim: bool = False, max_reads_per_strand: Optional[int] = None,
                 error_rate_pre_umi: int = 45, error_rate_post_umi: int = 40,
                 seed: Optional[int] = 42, kernel=None,
                 track_rejects: bool = False, methylation_mode=None,
                 reference=None, ref_names=None):
        self.prefix = read_name_prefix
        self.read_group_id = read_group_id
        self.min_total, self.min_xy, self.min_yx = parse_min_reads(min_reads)
        self.produce_per_base_tags = produce_per_base_tags
        # SS caller: min_reads=1, min_consensus_qual=Q2 (duplex_caller.rs:400-420)
        # methylation rides the SS caller's options/reference, exactly like
        # the reference's with_methylation (duplex_caller.rs:437-448)
        ss_opts = VanillaOptions(
            error_rate_pre_umi=error_rate_pre_umi,
            error_rate_post_umi=error_rate_post_umi,
            min_input_base_quality=min_input_base_quality,
            min_reads=1, max_reads=max_reads_per_strand,
            produce_per_base_tags=produce_per_base_tags, seed=seed, trim=trim,
            min_consensus_base_quality=MIN_PHRED,
            methylation_mode=methylation_mode)
        self.ss = VanillaConsensusCaller(read_name_prefix, read_group_id, ss_opts,
                                         kernel=kernel, reference=reference,
                                         ref_names=ref_names)
        self.kernel = self.ss.kernel
        self.stats = CallerStats()
        self._init_rejects(track_rejects)
        self._builder = RecordBuilder()
        self._ordinal = 0

    def merged_stats(self) -> CallerStats:
        """Duplex-level stats plus SS-level rejections (e.g. MinorityAlignment
        recorded by the inner vanilla caller's alignment filter)."""
        merged = CallerStats(input_reads=self.stats.input_reads,
                             consensus_reads=self.stats.consensus_reads,
                             rejected=dict(self.stats.rejected))
        for k, v in self.ss.stats.rejected.items():
            merged.reject(k, v)
        return merged

    # ---------------------------------------------------------------- stage 1 prep

    def _prepare_molecule(self, base_mi: str, a_records, b_records):
        """Host prep for one molecule: validation + the four SS jobs
        (process_group, duplex_caller.rs:1755-1983). Returns a dict or None."""
        self.stats.input_reads += len(a_records) + len(b_records)
        ordinal = self._ordinal
        self._ordinal += 1

        # fragments are rejected as NonPairedReads (duplex_caller.rs:2256-2268)
        frags = sum(1 for r in a_records + b_records if not r.flag & FLAG_PAIRED)
        if frags:
            self.stats.reject("FragmentRead", frags)
            self._reject_records(r for r in a_records + b_records
                                 if not r.flag & FLAG_PAIRED)
            a_records = [r for r in a_records if r.flag & FLAG_PAIRED]
            b_records = [r for r in b_records if r.flag & FLAG_PAIRED]

        if not a_records and not b_records:
            return None

        def is_r1(r):
            return (r.flag & FLAG_PAIRED) and (r.flag & FLAG_FIRST)

        def is_r2(r):
            return (r.flag & FLAG_PAIRED) and (r.flag & FLAG_LAST)

        num_a = sum(1 for r in a_records if is_r1(r))
        num_b = sum(1 for r in b_records if is_r1(r))
        num_xy, num_yx = max(num_a, num_b), min(num_a, num_b)
        if not (self.min_total <= num_xy + num_yx and self.min_xy <= num_xy
                and self.min_yx <= num_yx):
            self.stats.reject("InsufficientReads", len(a_records) + len(b_records))
            self._reject_records(a_records)
            self._reject_records(b_records)
            return None

        ab_r1 = [r for r in a_records if is_r1(r)]
        ab_r2 = [r for r in a_records if is_r2(r)]
        ba_r1 = [r for r in b_records if is_r1(r)]
        ba_r2 = [r for r in b_records if is_r2(r)]

        # strand-orientation validation (duplex_caller.rs:1830-1860)
        def same_strand(recs):
            strands = {bool(r.flag & FLAG_REVERSE) for r in recs}
            return len(strands) <= 1

        if a_records and b_records:
            if not same_strand(ab_r1 + ba_r2) or not same_strand(ab_r2 + ba_r1):
                self.stats.reject("PotentialCollision",
                                  len(a_records) + len(b_records))
                self._reject_records(a_records)
                self._reject_records(b_records)
                return None

        # X = AB-R1 + BA-R2, Y = AB-R2 + BA-R1: convert + filter together.
        # Reads dropped here contribute to no consensus even when the
        # molecule succeeds, so they are rejected immediately; prep_ids keeps
        # a later molecule-level failure from double-rejecting them.
        prep_ids = set()

        def prep_reject(recs):
            if self.track_rejects:
                recs = list(recs)
                prep_ids.update(map(id, recs))
                self._reject_records(recs)

        def to_sources(recs):
            out = []
            for i, r in enumerate(recs):
                sr = self.ss._create_source_read(r, i, num_bases_extending_past_mate(r))
                if sr is not None:
                    out.append(sr)
                else:  # unconvertible: 0xFF quals / zero length
                    prep_reject([r])
            return out

        def filter_alignment(sources, raws_list):
            kept = self.ss._filter_by_alignment(sources)
            if len(kept) < len(sources):
                kept_idx = {sr.original_idx for sr in kept}
                prep_reject(raws_list[sr.original_idx] for sr in sources
                            if sr.original_idx not in kept_idx)
            return kept

        x_raws = ab_r1 + ba_r2
        y_raws = ab_r2 + ba_r1
        filtered_x = filter_alignment(to_sources(x_raws), x_raws)
        filtered_y = filter_alignment(to_sources(y_raws), y_raws)

        f_ab_r1 = [sr for sr in filtered_x if sr.flags & FLAG_FIRST]
        f_ba_r2 = [sr for sr in filtered_x if not sr.flags & FLAG_FIRST]
        f_ab_r2 = [sr for sr in filtered_y if not sr.flags & FLAG_FIRST]
        f_ba_r1 = [sr for sr in filtered_y if sr.flags & FLAG_FIRST]

        ab_umi, ba_umi = f"{base_mi}/A", f"{base_mi}/B"
        jobs = {}
        for key, umi, srs in (("ab_r1", ab_umi, f_ab_r1), ("ab_r2", ab_umi, f_ab_r2),
                              ("ba_r1", ba_umi, f_ba_r1), ("ba_r2", ba_umi, f_ba_r2)):
            job = self.ss.job_from_source_reads(umi, R1, srs, ordinal=ordinal,
                                               keep_source_reads=True)
            if job is not None:
                jobs[key] = job

        raws = {
            "ab_r1": [x_raws[sr.original_idx] for sr in f_ab_r1],
            "ba_r2": [x_raws[sr.original_idx] for sr in f_ba_r2],
            "ab_r2": [y_raws[sr.original_idx] for sr in f_ab_r2],
            "ba_r1": [y_raws[sr.original_idx] for sr in f_ba_r1],
        }
        return {"base_mi": base_mi, "jobs": jobs, "raws": raws,
                "n_records": len(a_records) + len(b_records),
                # molecule-failure rejects: only reads not already rejected
                # during prep (built only when tracking)
                "all_records": [r for r in list(a_records) + list(b_records)
                                if id(r) not in prep_ids]
                if self.track_rejects else ()}

    # ---------------------------------------------------------------- stage 2

    def _has_min_reads(self, dup: DuplexConsensusRead) -> bool:
        num_a = dup.ab_consensus.max_depth()
        num_b = dup.ba_consensus.max_depth() if dup.ba_consensus is not None else 0
        xy, yx = max(num_a, num_b), min(num_a, num_b)
        return (self.min_total <= xy + yx and self.min_xy <= xy and self.min_yx <= yx)

    def _combine_molecule(self, mol, consensus):
        """Stage-2 for one molecule given its SS consensus dict. Returns record
        bytes list (R1 then R2) or None (match arms, duplex_caller.rs:2017-2237)."""
        c = consensus
        ab_r1, ab_r2 = c.get("ab_r1"), c.get("ab_r2")
        ba_r1, ba_r2 = c.get("ba_r1"), c.get("ba_r2")
        raws = mol["raws"]
        base_mi = mol["base_mi"]

        if ab_r1 is not None and ab_r2 is not None and ba_r1 is not None \
                and ba_r2 is not None:
            r1_sources = list(ab_r1.source_reads or []) + list(ba_r2.source_reads or [])
            r2_sources = list(ab_r2.source_reads or []) + list(ba_r1.source_reads or [])
            dr1 = duplex_combine(ab_r1, ba_r2, r1_sources or None)
            dr2 = duplex_combine(ab_r2, ba_r1, r2_sources or None)
            if dr1 is not None and dr2 is not None:
                if self._has_min_reads(dr1) and self._has_min_reads(dr2):
                    recs = [
                        self._build_record(dr1, R1, base_mi, raws["ab_r1"], raws["ba_r2"]),
                        self._build_record(dr2, R2, base_mi, raws["ab_r2"], raws["ba_r1"]),
                    ]
                    self.stats.consensus_reads += 2
                    return recs
                self.stats.reject("InsufficientReads", mol["n_records"])
                self._reject_records(mol.get("all_records", ()))
                return None
        elif ab_r1 is not None and ab_r2 is not None and ba_r1 is None \
                and ba_r2 is None:
            if self.min_yx == 0:
                dr1 = duplex_combine(ab_r1, None)
                dr2 = duplex_combine(ab_r2, None)
                if dr1 is not None and dr2 is not None:
                    recs = [
                        self._build_record(dr1, R1, base_mi, raws["ab_r1"], []),
                        self._build_record(dr2, R2, base_mi, raws["ab_r2"], []),
                    ]
                    self.stats.consensus_reads += 2
                    return recs
        elif ab_r1 is None and ab_r2 is None and ba_r1 is not None \
                and ba_r2 is not None:
            # BA-only: output R1 derives from BA-R2, R2 from BA-R1 (rs:2179-2231)
            if self.min_yx == 0:
                dr1 = duplex_combine(None, ba_r2)
                dr2 = duplex_combine(None, ba_r1)
                if dr1 is not None and dr2 is not None:
                    recs = [
                        self._build_record(dr1, R1, base_mi, [], raws["ba_r2"]),
                        self._build_record(dr2, R2, base_mi, [], raws["ba_r1"]),
                    ]
                    self.stats.consensus_reads += 2
                    return recs
        self.stats.reject("InsufficientReads", mol["n_records"])
        self._reject_records(mol.get("all_records", ()))
        return None

    # ---------------------------------------------------------------- output

    def _build_record(self, dup: DuplexConsensusRead, read_type: int, base_mi: str,
                      raws_a, raws_b) -> bytes:
        """duplex_read_into (duplex_caller.rs:1056-1249); tag order preserved."""
        from ..constants import CODE_TO_BASE

        b = self._builder
        name = f"{self.prefix}:{base_mi}".encode()
        seq = CODE_TO_BASE[np.minimum(dup.bases, N_CODE)].tobytes()
        b.start_unmapped(name, _TYPE_FLAGS[read_type], seq, dup.quals)
        b.tag_str(b"MI", base_mi.encode())
        b.tag_str(b"RG", self.read_group_id.encode())

        def strand_metrics(c: Optional[VanillaConsensusRead]):
            if c is None or not len(c.depths):
                return 0, 0, np.float32(0)
            d = np.minimum(c.depths, I16_MAX)
            e = np.minimum(c.errors, I16_MAX)
            total_d = int(d.sum())
            rate = np.float32(int(e.sum())) / np.float32(total_d) if total_d else np.float32(0)
            return int(d.max()), int(d.min()), rate

        ab, ba = dup.ab_consensus, dup.ba_consensus
        a_max, a_min, a_rate = strand_metrics(ab)
        b.tag_int(b"aD", a_max)
        b.tag_float(b"aE", float(a_rate))
        b.tag_int(b"aM", a_min)
        if self.produce_per_base_tags:
            b.tag_str(b"ac", CODE_TO_BASE[np.minimum(ab.bases, N_CODE)].tobytes())
            b.tag_array_i16(b"ad", np.minimum(ab.depths, I16_MAX))
            b.tag_array_i16(b"ae", np.minimum(ab.errors, I16_MAX))
            b.tag_str(b"aq", (ab.quals + 33).astype(np.uint8).tobytes())

        b_max, b_min, b_rate = strand_metrics(ba)
        b.tag_int(b"bD", b_max)
        b.tag_float(b"bE", float(b_rate))
        b.tag_int(b"bM", b_min)
        if self.produce_per_base_tags and ba is not None:
            b.tag_str(b"bc", CODE_TO_BASE[np.minimum(ba.bases, N_CODE)].tobytes())
            b.tag_array_i16(b"bd", np.minimum(ba.depths, I16_MAX))
            b.tag_array_i16(b"be", np.minimum(ba.errors, I16_MAX))
            b.tag_str(b"bq", (ba.quals + 33).astype(np.uint8).tobytes())

        # combined cD/cE/cM: per-strand per-base clamp before summing (rs:1188-1215)
        length = len(dup.bases)
        comb = np.minimum(ab.depths[:length], I16_MAX).astype(np.int64)
        if ba is not None:
            comb = comb + np.minimum(ba.depths[:length], I16_MAX)
        total_d = int(comb.sum())
        total_e = int(np.minimum(dup.errors, I16_MAX).sum())
        rate = np.float32(total_e) / np.float32(total_d) if total_d else np.float32(0)
        b.tag_int(b"cD", int(comb.max()) if length else 0)
        b.tag_float(b"cE", float(rate))
        b.tag_int(b"cM", int(comb.min()) if length else 0)

        # RX: strand-reoriented UMI consensus (rs:1217-1249)
        first_of_pair = read_type == R1
        all_umis = []
        for raw in list(raws_a) + list(raws_b):
            rx = raw.get_str(b"RX")
            if rx is None:
                continue
            is_first = bool(raw.flag & FLAG_FIRST)
            if is_first == first_of_pair:
                all_umis.append(rx)
            else:
                all_umis.append("-".join(reversed(rx.split("-"))))
        if all_umis:
            b.tag_str(b"RX", consensus_umis(all_umis).encode())

        # methylation tags (EM-Seq/TAPS; duplex_caller.rs:1251-1312): per
        # strand am/au/at (top) / bm/bu/bt (bottom), then combined MM/ML +
        # cu/ct. BA-only molecules store their strand in ab_consensus, so
        # per-strand tags switch to bottom orientation.
        if dup.methylation is not None:
            from . import methylation as meth_mod

            mode = self.ss.options.methylation_mode
            is_top = not dup.is_ba_only
            ab_meth = ab.methylation
            if ab_meth is not None:
                mm_tag, u_tag, t_tag = (b"am", b"au", b"at") if is_top \
                    else (b"bm", b"bu", b"bt")
                got = meth_mod.build_mm_ml(ab.bases, ab_meth[0], is_top, mode)
                if got is not None:
                    b.tag_str(mm_tag, got[0].encode())
                b.tag_array_i16(u_tag, ab_meth[0].cu())
                b.tag_array_i16(t_tag, ab_meth[0].ct())
            if ba is not None and ba.methylation is not None:
                ba_ann = ba.methylation[0]
                got = meth_mod.build_mm_ml(ba.bases, ba_ann, False, mode)
                if got is not None:
                    b.tag_str(b"bm", got[0].encode())
                b.tag_array_i16(b"bu", ba_ann.cu())
                b.tag_array_i16(b"bt", ba_ann.ct())
            got = meth_mod.build_mm_ml(dup.bases, dup.methylation, is_top,
                                       mode)
            if got is not None:
                b.tag_str(b"MM", got[0].encode())
                b.tag_array_u8(b"ML", np.frombuffer(got[1], dtype=np.uint8))
            b.tag_array_i16(b"cu", dup.methylation.cu())
            b.tag_array_i16(b"ct", dup.methylation.ct())
        return b.finish()

    # ---------------------------------------------------------------- driver

    def call_groups(self, groups) -> list:
        """Process [(base_mi, a_records, b_records)] -> consensus record bytes.

        All molecules' SS jobs run as one batched device pass; stage 2 follows on
        host. Output order: molecule order, R1 then R2.
        """
        molecules = []
        for base_mi, a_records, b_records in groups:
            mol = self._prepare_molecule(base_mi, a_records, b_records)
            if mol is not None:
                molecules.append(mol)
        all_jobs = []
        for mol in molecules:
            for job in mol["jobs"].values():
                all_jobs.append(job)
        results = self.ss._run_jobs(all_jobs) if all_jobs else []
        it = iter(results)
        out = []
        for mol in molecules:
            consensus = {}
            for key, job in mol["jobs"].items():
                consensus[key] = self.ss.result_to_consensus_read(job, next(it))
            recs = self._combine_molecule(mol, consensus)
            if recs:
                out.extend(recs)
        return out


def iter_duplex_groups(records, tag: bytes = b"MI", record_filter=None):
    """Group consecutive records by base MI -> (base_mi, a_records, b_records).

    Input must be grouped by base MI (the paired-strategy group output keeps /A and
    /B of a molecule adjacent, mi_group.rs contract)."""
    current_base = None
    a_recs, b_recs = [], []
    for rec in records:
        if record_filter is not None and not record_filter(rec):
            continue
        mi = rec.get_str(tag)
        if mi is None:
            raise ValueError(f"record {rec.name!r} missing {tag.decode()} tag")
        base, strand = split_mi(mi)
        if base != current_base:
            if current_base is not None and (a_recs or b_recs):
                yield current_base, a_recs, b_recs
            current_base = base
            a_recs, b_recs = [], []
        (a_recs if strand == "A" else b_recs).append(rec)
    if current_base is not None and (a_recs or b_recs):
        yield current_base, a_recs, b_recs
