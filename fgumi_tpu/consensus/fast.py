"""Vectorized simplex consensus path over RecordBatch inputs.

The host-throughput answer to the reference's raw-byte pipeline discipline
(/root/reference/src/lib/unified_pipeline/bam.rs Decode/Process steps +
crates/fgumi-consensus/src/vanilla_caller.rs:1119-1331): per-record work is
done natively in batch (fgumi_tpu.native.batch), per-family work on numpy
index slices, and the likelihood loop on the device kernel.

Semantics contract: byte-identical output and identical rejection statistics
to VanillaConsensusCaller.call_groups on the same stream (tested in
tests/test_fast_simplex.py). Families the vectorized path cannot express
(methylation mode, quality trimming, non-uniform CIGARs needing the
most-common-alignment filter) fall back to the slow path per group.
"""

import numpy as np

from ..core import cigar as cigar_utils
from ..io.bam import (FLAG_FIRST, FLAG_LAST, FLAG_MATE_UNMAPPED, FLAG_PAIRED,
                      FLAG_REVERSE, FLAG_SECONDARY, FLAG_SUPPLEMENTARY,
                      FLAG_UNMAPPED)
from ..native import batch as nb
from ..ops import oracle
from .overlapping import (AGREEMENT_CODES, DISAGREEMENT_CODES,
                          add_native_overlap_stats)
from .simple_umi import consensus_umis_batch
from .vanilla import (FRAGMENT, R1, R2, _TYPE_FLAGS, VanillaConsensusCaller)

# read-type -> record flags as an indexable array (serialize is table-driven)
_TYPE_FLAGS_ARR = np.array([_TYPE_FLAGS[FRAGMENT], _TYPE_FLAGS[R1],
                            _TYPE_FLAGS[R2]], dtype=np.int32)

def resolve_chunk(chunk) -> bytes:
    """Wire bytes of a process_batch output item (resolving deferred device
    work — the fetch+serialize half of a batch runs here, typically on the
    writer stage so transfers overlap the next batch's host prep)."""
    return chunk if isinstance(chunk, bytes) else chunk.resolve()


def split_row_balanced(counts, dp):
    """Job boundaries for dp contiguous row-balanced shards over segments of
    `counts` rows each: (dp+1,) indices into the job list.

    The target-crossing job goes to whichever side leaves the row split
    closer to the target (plain searchsorted+1 can collapse a 2-job batch
    onto one device). Shared by the simplex and duplex sharded dispatches.
    """
    n_jobs = len(counts)
    cum = np.cumsum(counts)
    total = int(cum[-1])
    targets = (np.arange(1, dp) * total) // dp
    i = np.searchsorted(cum, targets, side="left")
    prev = np.where(i > 0, cum[np.maximum(i - 1, 0)], 0)
    jb = i + ((cum[np.minimum(i, n_jobs - 1)] - targets)
              <= (targets - prev))
    jb = np.concatenate(([0], jb, [n_jobs]))
    return np.minimum(np.maximum.accumulate(jb), n_jobs)


def pack_shards(codes_d, quals_d, starts, jb, L_max):
    """Pack dense (rows, L) segment data into the (dp, N_max, L) sharded
    layout for device_call_segments_sharded.

    Returns (codes3d, quals3d, seg2d, shard_starts, n_jobs, F_loc). One copy
    of the subtle pad invariants — rows pad with N/Q0, pad rows carry the
    shard's LAST real segment id (so they fold into an existing segment and
    cannot mint phantom families), and N_max/F_loc round up to pow2 for the
    compile cache. Shared by the simplex and duplex sharded dispatches.
    """
    dp = len(jb) - 1
    shard_starts = [starts[jb[d]:jb[d + 1] + 1] - starts[jb[d]]
                    for d in range(dp)]
    n_rows = [int(s[-1]) for s in shard_starts]
    n_jobs = [int(jb[d + 1] - jb[d]) for d in range(dp)]
    from ..ops.kernel import DEVICE_STATS, _pad_rows

    N_max = _pad_rows(max(max(n_rows), 1))
    F_loc = 1 << (max(max(n_jobs), 1) - 1).bit_length()
    DEVICE_STATS.add_pad(sum(n_rows), dp * N_max)

    codes3d = np.full((dp, N_max, L_max), 4, dtype=np.uint8)
    quals3d = np.zeros((dp, N_max, L_max), dtype=np.uint8)
    seg2d = np.zeros((dp, N_max), dtype=np.int32)
    for d in range(dp):
        lo, hi = int(starts[jb[d]]), int(starts[jb[d + 1]])
        n = n_rows[d]
        codes3d[d, :n] = codes_d[lo:hi]
        quals3d[d, :n] = quals_d[lo:hi]
        seg2d[d, :n] = np.repeat(
            np.arange(n_jobs[d], dtype=np.int32),
            np.diff(shard_starts[d]))
        seg2d[d, n:] = max(n_jobs[d] - 1, 0)
    return codes3d, quals3d, seg2d, shard_starts, n_jobs, F_loc


def pack_shards_sp(codes_d, quals_d, starts, jb, L_max, sp):
    """Pack dense segment data into the (dp, sp, N_sp, L) layout for
    device_call_segments_dp_sp.

    Each dp shard's rows split into sp contiguous chunks (segments may span
    chunk boundaries — partial segment sums psum exactly); every chunk pads
    to the common pow2 N_sp with all-N rows carrying the chunk's last real
    segment id (or 0 for empty chunks). Segment ids stay shard-global so the
    psum-combined output is (dp, F_loc, L) exactly like the sp=1 layout."""
    dp = len(jb) - 1
    shard_starts = [starts[jb[d]:jb[d + 1] + 1] - starts[jb[d]]
                    for d in range(dp)]
    n_rows = [int(s[-1]) for s in shard_starts]
    n_jobs = [int(jb[d + 1] - jb[d]) for d in range(dp)]
    chunk = [-(-max(n, 1) // sp) for n in n_rows]
    from ..ops.kernel import DEVICE_STATS, _pad_rows

    N_sp = _pad_rows(max(chunk)) if max(chunk) > 1 else 1
    F_loc = 1 << (max(max(n_jobs), 1) - 1).bit_length()
    DEVICE_STATS.add_pad(sum(n_rows), dp * sp * N_sp)

    codes4 = np.full((dp, sp, N_sp, L_max), 4, dtype=np.uint8)
    quals4 = np.zeros((dp, sp, N_sp, L_max), dtype=np.uint8)
    seg3 = np.zeros((dp, sp, N_sp), dtype=np.int32)
    for d in range(dp):
        base = int(starts[jb[d]])
        n = n_rows[d]
        seg_local = np.repeat(np.arange(n_jobs[d], dtype=np.int32),
                              np.diff(shard_starts[d]))
        for s in range(sp):
            lo = min(s * chunk[d], n)
            hi = min(lo + chunk[d], n)
            m = hi - lo
            if m:
                codes4[d, s, :m] = codes_d[base + lo:base + hi]
                quals4[d, s, :m] = quals_d[base + lo:base + hi]
                seg3[d, s, :m] = seg_local[lo:hi]
                seg3[d, s, m:] = seg_local[hi - 1]
    return codes4, quals4, seg3, shard_starts, n_jobs, F_loc


def _ranges(lo, counts):
    """Concatenated arange(lo_i, lo_i + counts_i) without a Python loop."""
    counts = np.asarray(counts, dtype=np.int64)
    total = int(counts.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64)
    lo = np.asarray(lo, dtype=np.int64)
    keep = counts > 0
    lo_k = lo[keep]
    c_k = counts[keep]
    step = np.ones(total, dtype=np.int64)
    firsts = np.concatenate(([0], np.cumsum(c_k)[:-1]))
    step[firsts] = lo_k
    # later range-starts jump from the previous range's last value
    step[firsts[1:]] -= lo_k[:-1] + c_k[:-1] - 1
    return np.cumsum(step)


class _JobTable:
    """Array-form job list for one batch span — no per-job Python objects.

    Jobs (consensus outputs) are rows of parallel arrays, in output order:
    per group, fragment first, then the R1/R2 pair (vanilla.py:377-386).
    `vlo`/`count` slice the shared row pool: `pool_rows` holds span-relative
    row indices into the packed code/qual arrays, `pool_span` the same rows
    as absolute batch record indices (for RX lookups). `mi_rec` is the batch
    record whose MI tag value provides the job's UMI bytes (the group's
    first record) — serialization reads it straight out of the batch buffer.
    """

    __slots__ = ("count", "vlo", "read_type", "cons_len", "mi_rec",
                 "pool_rows", "pool_span")

    def __init__(self, count, vlo, read_type, cons_len, mi_rec, pool_rows,
                 pool_span):
        self.count = count
        self.vlo = vlo
        self.read_type = read_type
        self.cons_len = cons_len
        self.mi_rec = mi_rec
        self.pool_rows = pool_rows
        self.pool_span = pool_span

    def __len__(self):
        return len(self.count)


def _table_from_legacy(entries, span):
    """_JobTable from (key, group_start, (read_type, rows, cons_len)) tuples
    already in output order (the rejects-tracking all-scan path)."""
    J = len(entries)
    if J == 0:
        e64 = np.empty(0, dtype=np.int64)
        return _JobTable(e64, e64, np.empty(0, dtype=np.int8),
                         np.empty(0, dtype=np.int32), e64, e64, e64)
    counts = np.fromiter((len(jg[1]) for _, _, jg in entries), np.int64, J)
    vlo = np.concatenate(([0], np.cumsum(counts)[:-1]))
    rt = np.fromiter((jg[0] for _, _, jg in entries), np.int8, J)
    cl = np.fromiter((jg[2] for _, _, jg in entries), np.int32, J)
    mi = np.fromiter((span[s] for _, s, _ in entries), np.int64, J)
    pool = np.concatenate([jg[1] for _, _, jg in entries]).astype(np.int64)
    return _JobTable(counts, vlo, rt, cl, mi, pool, span[pool])


class _PendingChunk:
    """Deferred half of a batch: fetch packed device results, recompute
    depth/errors on host, apply thresholds, serialize (SURVEY §7 step 4
    double-buffering: dispatch happens in process_batch, this completes it)."""

    __slots__ = ("fast", "batch", "jobs", "pending", "blocks")

    def __init__(self, fast, batch, jobs, pending, blocks0=()):
        self.fast = fast
        self.batch = batch
        self.jobs = jobs  # a _JobTable
        self.pending = pending
        # (job_idxs, bases, quals, depth32, errors32) row blocks; starts with
        # the host-path blocks (single-read jobs) from _dispatch_jobs
        self.blocks = list(blocks0)

    def resolve(self) -> bytes:
        fast = self.fast
        if fast.filter_stage is not None:
            # fused consensus→filter route (ISSUE 11): verdicts from the
            # device stats fetch (or host columns), survivors-only gather,
            # survivors-only serialization — consensus/device_filter.py
            return fast.filter_stage.resolve_chunk(self)
        caller = fast.caller
        kernel = caller.kernel
        if self.pending is None:
            pass
        elif self.pending[0] == "seg":
            _, idxs, starts, codes_d, quals_d, dev = self.pending
            winner, qual, depth, errors = kernel.resolve_segments(
                dev, codes_d, quals_d, starts)
            self._assign(idxs, winner, qual, depth, errors)
        elif self.pending[0] == "cols":
            _, idxs, pending = self.pending
            winner, qual, depth, errors = kernel.resolve_hard_columns(
                pending)
            self._assign(idxs, winner, qual, depth, errors)
        else:  # "segw": the wire ticket, single-device or mesh-sharded
            _, idxs, starts, codes_d, quals_d, ticket = self.pending
            winner, qual, depth, errors = kernel.resolve_segments_wire(
                ticket, codes_d, quals_d, starts)
            self._assign(idxs, winner, qual, depth, errors)
        return fast._serialize_jobs(self.batch, self.jobs, self.blocks)

    def _assign(self, idxs, winner, qual, depth, errors):
        """Thresholds in one vectorized pass; rows are handed to the
        serializer as whole blocks (addresses computed per block, not per
        job — job.result stays None for block-backed jobs)."""
        opts = self.fast.caller.options
        bases_b, quals_b = oracle.apply_consensus_thresholds(
            winner, qual, depth, opts.min_reads,
            opts.min_consensus_base_quality)
        self.blocks.append((np.asarray(idxs, dtype=np.int64),
                            np.ascontiguousarray(bases_b),
                            np.ascontiguousarray(quals_b),
                            np.ascontiguousarray(depth, dtype=np.int32),
                            np.ascontiguousarray(errors, dtype=np.int32)))


class FastSimplexCaller:
    """Batch-vectorized simplex caller wrapping a VanillaConsensusCaller.

    The wrapped caller owns options/tables/kernel/stats/record-builder and
    serves as the per-group fallback, so statistics and output bytes are shared
    across both paths.
    """

    def __init__(self, caller: VanillaConsensusCaller, tag: bytes = b"MI",
                 overlap_caller=None, mesh=None, filter_stage=None):
        """`mesh`: optional jax Mesh with (dp, sp) axes — multi-read jobs
        dispatch through the shard_map-wrapped full-column wire kernels
        (families over dp with no collectives, each shard's read rows over
        sp with one psum combine; ops/kernel._dispatch_wire_mesh). None or
        a 1-device mesh = the legacy single-device path, bit for bit.
        `filter_stage`: a consensus/device_filter.SimplexFilterStage —
        the fused consensus→filter route (--device-filter): outputs are
        filtered before serialization, device-routed batches via the
        fused mask kernel with survivors-only fetch."""
        self.caller = caller
        self.tag = tag
        self.overlap_caller = overlap_caller  # OverlappingBasesConsensusCaller
        self.mesh = mesh if mesh is not None and mesh.size > 1 else None
        self.filter_stage = filter_stage
        # device/host routing is per batch via the adaptive cost model
        # (ops/router.py; FGUMI_TPU_ROUTE forces a side; the explicit
        # FGUMI_TPU_MAX_INFLIGHT escape hatch is honored inside
        # ROUTER.decide)
        opts = caller.options
        # conditions the vectorized conversion cannot express
        self._vector_ok = (not opts.trim and not opts.methylation_mode)
        self._carry = None  # (mi_bytes, [RawRecord]) spanning batch boundary
        self._palin_cache = {}  # cigar bytes -> simplified-CIGAR palindromicity

    # ------------------------------------------------------------------ driver

    def process_batch(self, batch, allow_unmapped: bool = False,
                      final: bool = False):
        """Consume one RecordBatch -> list of consensus record bytes.

        Groups are formed over records passing the consensus pre-group filter
        (core/grouper.py:13-23). The group spanning the batch boundary is
        carried (as RawRecords) until the next batch or `final`; it is
        processed via the slow path, with overlap correction applied there so
        pairs split across batches are still corrected.
        """
        flag = batch.flag
        keep = (flag & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY)) == 0
        if not allow_unmapped:
            is_mapped = (flag & FLAG_UNMAPPED) == 0
            mapped_mate = ((flag & FLAG_PAIRED) != 0) \
                & ((flag & FLAG_MATE_UNMAPPED) == 0)
            keep &= is_mapped | mapped_mate
        idx = np.nonzero(keep)[0]
        if len(idx) == 0:
            return self.flush() if final else []

        # every tag this engine reads for the batch, one native aux scan
        batch.prefetch_tags([self.tag, b"MC", b"RX"])
        mi_off, mi_len, _ = batch.tag_locs(self.tag)
        starts = nb.group_starts(batch.buf, np.ascontiguousarray(mi_off[idx]),
                                 mi_len[idx])
        bounds = np.append(starts, len(idx))
        n_total = len(bounds) - 1

        # does the first group continue the carried group?
        first_mi = batch.tag_bytes(self.tag, int(idx[bounds[0]]))
        merge_carry = self._carry is not None and self._carry[0] == first_mi
        if merge_carry:
            # materialize before any in-place correction of this batch
            self._carry[1].extend(batch.raw_records(idx[bounds[0]:bounds[1]]))

        # groups [g0, g1) run the vectorized path this call; the last group of
        # a non-final batch is deferred (it may continue into the next batch)
        g0 = 1 if merge_carry else 0
        g1 = n_total if final else max(n_total - 1, g0)
        deferred = None
        if not final and n_total - 1 >= g0:
            last = idx[bounds[n_total - 1]:bounds[n_total]]
            # materialize before in-place correction: the deferred group is
            # corrected exactly once, on the slow path, when it completes
            deferred = (batch.tag_bytes(self.tag, int(last[0])),
                        batch.raw_records(last))

        out = []
        if self._carry is not None:
            # the carry completes unless the merged group is still the open
            # tail of a non-final batch (merge_carry and no group follows)
            if (not merge_carry) or final or n_total >= 2:
                out.extend(self._call_slow_group(*self._carry))
                self._carry = None

        if g1 > g0:
            # native in-place overlap correction only for the complete groups
            if self.overlap_caller is not None:
                self._overlap_correct(batch, idx, bounds, g0, g1)
            out.extend(self._process_groups(batch, idx, bounds, g0, g1))

        if deferred is not None:
            self._carry = deferred
        if final:
            out.extend(self.flush())
        return out

    def flush(self):
        """Emit any carried boundary group (call after the last batch)."""
        if self._carry is None:
            return []
        mi, recs = self._carry
        self._carry = None
        return self._call_slow_group(mi, recs)

    def _call_slow_group(self, mi_bytes, records):
        """Slow-path one group, with Python overlap correction first (the
        carried group's pairs may span batch buffers). Returns wire chunks."""
        if self.overlap_caller is not None:
            from .overlapping import apply_overlapping_consensus

            records = apply_overlapping_consensus(records, self.overlap_caller)
        recs = self.caller.call_groups([(mi_bytes.decode(), records)])
        if not recs:
            return []
        return self._post_slow(
            [b"".join(len(r).to_bytes(4, "little") + r for r in recs)])

    def _post_slow(self, chunks):
        """Fused-filter pass over slow-path record blobs (already-serialized
        complete groups); identity when no filter stage is attached."""
        if self.filter_stage is None or not chunks:
            return chunks
        out = [self.filter_stage.filter_records_blob(c) for c in chunks]
        return [c for c in out if c]

    # ------------------------------------------------------------ overlap corr

    def _overlap_correct(self, batch, idx, bounds, g0, g1):
        overlap_correct_span(batch, idx, bounds, g0, g1, self.overlap_caller)

    # ------------------------------------------------------------------ groups

    def _process_groups(self, batch, idx, bounds, g0, g1):
        caller = self.caller
        opts = caller.options

        if not self._vector_ok:
            # trim / methylation modes: whole-group slow path, stream order
            groups = []
            for g in range(g0, g1):
                members = idx[bounds[g]:bounds[g + 1]]
                mi = batch.tag_bytes(self.tag, int(members[0]))
                groups.append((mi.decode(), batch.raw_records(members)))
            recs = caller.call_groups(groups)
            if not recs:
                return []
            return self._post_slow(
                [b"".join(len(r).to_bytes(4, "little") + r for r in recs)])

        # batch-wide native prep over the kept records of the processed groups
        span = idx[bounds[g0]:bounds[g1]]
        mc_off, mc_len, _ = batch.tag_locs_str(b"MC")
        clips = nb.mate_clips(
            batch.buf, np.ascontiguousarray(batch.cigar_off[span]),
            batch.n_cigar[span], batch.flag[span], batch.ref_id[span],
            batch.pos[span], batch.next_ref_id[span], batch.next_pos[span],
            batch.tlen[span], np.ascontiguousarray(mc_off[span]),
            mc_len[span])
        # stride is a multiple of 32 so every bucket width Lb <= stride
        stride = max(-(-int(batch.l_seq[span].max()) // 32) * 32, 32)
        reverse = ((batch.flag[span] & FLAG_REVERSE) != 0).astype(np.uint8)
        codes, quals, final_len = nb.pack_reads(
            batch.buf, np.ascontiguousarray(batch.seq_off[span]),
            np.ascontiguousarray(batch.qual_off[span]), batch.l_seq[span],
            reverse, clips, opts.min_input_base_quality, stride)

        # span-relative views
        flag_s = batch.flag[span]
        paired = (flag_s & FLAG_PAIRED) != 0
        # read type: fragment / R1 / R2; paired-but-neither drops silently
        # (vanilla.py:296-304 subgroup dict semantics)
        rtype = np.full(len(span), -1, dtype=np.int8)
        rtype[~paired] = FRAGMENT
        rtype[paired & ((flag_s & FLAG_FIRST) != 0)] = R1
        rtype[paired & ((flag_s & FLAG_FIRST) == 0)
              & ((flag_s & FLAG_LAST) != 0)] = R2

        # span-wide CIGAR-equality runs: group g is CIGAR-uniform iff no run
        # boundary falls strictly inside (s, e) — avoids per-subgroup scans
        cig_runs = nb.group_starts(
            batch.buf, np.ascontiguousarray(batch.cigar_off[span]),
            (4 * batch.n_cigar[span]).astype(np.int32))
        rel_bounds = bounds - bounds[g0]
        runs_lo = np.searchsorted(cig_runs, rel_bounds[g0:g1], side="right")
        runs_hi = np.searchsorted(cig_runs, rel_bounds[g0 + 1:g1 + 1],
                                  side="left")
        group_uniform = runs_hi == runs_lo

        # per-group preparation: vectorized common path; the per-group Python
        # scan remains for rejects-tracking mode and for groups needing
        # downsampling or the most-common-alignment filter
        if caller.track_rejects:
            legacy = []
            for g in range(g0, g1):
                s, e = rel_bounds[g], rel_bounds[g + 1]
                jobs_g = []
                self._prepare_group_fast(batch, span, s, e, rtype, final_len,
                                         jobs_g,
                                         bool(group_uniform[g - g0]))
                legacy.extend(((g - g0) * 3 + i, int(s), jg)
                              for i, jg in enumerate(jobs_g))
            table = _table_from_legacy(legacy, span)
        else:
            # rel_bounds is already span-relative (rel_bounds[g0] == 0)
            gb = rel_bounds[g0:g1 + 1]
            table = self._prepare_groups_vec(batch, span, gb, rtype,
                                             final_len, group_uniform)

        if len(table) == 0:
            return []
        pending, blocks0 = self._dispatch_jobs(codes, quals, table)
        return [_PendingChunk(self, batch, table, pending, blocks0)]

    def _prepare_groups_vec(self, batch, span, gb, rtype, final_len,
                            group_uniform):
        """Vectorized _prepare_group_fast over all groups of the span.

        gb: (nG+1,) span-relative group boundaries. Groups that need the
        seeded downsample or the most-common-alignment filter fall back to
        the per-group scan (identical semantics); everything else — type
        subgrouping, min-reads/zero-length rejection, consensus length,
        orphan handling — happens in whole-span array passes.

        Returns a _JobTable (jobs in output order, arrays only).
        """
        caller = self.caller
        opts = caller.options
        stats = caller.stats
        min_reads = opts.min_reads
        nG = len(gb) - 1
        sizes = np.diff(gb)
        ord0 = caller._group_ordinal
        caller._group_ordinal += nG

        small = sizes < min_reads
        downs = (np.zeros(nG, dtype=bool) if opts.max_reads is None
                 else sizes > opts.max_reads)

        # candidate rows: valid type, in a group subject to seg analysis
        g_of_row = np.repeat(np.arange(nG), sizes)
        row_ok = (~small & ~downs)[g_of_row] & (rtype >= 0)
        er = np.nonzero(row_ok)[0]
        legacy_g = downs.copy()
        nseg = 0
        if len(er):
            key = g_of_row[er] * 4 + rtype[er]
            order = np.argsort(key, kind="stable")
            srows = er[order]          # seg-grouped; original order within seg
            skey = key[order]
            seg_first = np.concatenate(([True], skey[1:] != skey[:-1]))
            seg_of_row = np.cumsum(seg_first) - 1
            seg_key = skey[seg_first]
            nseg = len(seg_key)
            seg_g = seg_key >> 2
            seg_t = (seg_key & 3).astype(np.int8)
            c0 = np.bincount(seg_of_row, minlength=nseg)

            valid_row = final_len[srows] > 0
            c1 = np.bincount(seg_of_row[valid_row], minlength=nseg)
            alive0 = c0 >= min_reads
            alive = alive0 & (c1 >= min_reads)

            vrows = srows[valid_row]   # compacted valid rows, seg-grouped
            vstarts = np.concatenate(([0], np.cumsum(c1)))
            span_v = span[vrows]
            vlens = final_len[vrows]

            # need-filter analysis (matches _prepare_group_fast): a seg needs
            # the alignment filter when its valid rows' CIGARs differ, or are
            # uniform but mixed-strand with a non-palindromic simplified CIGAR
            guniform_seg = group_uniform[seg_g]
            need = np.zeros(nseg, dtype=bool)
            nonempty = c1 > 0
            first_valid = np.zeros(nseg, dtype=np.int64)
            first_valid[nonempty] = vrows[vstarts[:-1][nonempty]]
            check = alive & ~guniform_seg
            if check.any():
                co = batch.cigar_off
                cl = (4 * batch.n_cigar).astype(np.int32)
                rep_first = np.repeat(span[first_valid], c1)
                eq = nb.ranges_equal(batch.buf, co[span_v], cl[span_v],
                                     co[rep_first], cl[rep_first])
                seg_cig_uniform = np.ones(nseg, dtype=bool)
                seg_cig_uniform[nonempty] = np.minimum.reduceat(
                    eq, vstarts[:-1][nonempty]).astype(bool)
                need = check & ~seg_cig_uniform
                if need.any():
                    # all-single-op-M segs (ragged read lengths, e.g. 80M vs
                    # 100M) are mutually prefix-compatible after simplify:
                    # the most-common-alignment filter provably keeps every
                    # read, so skip it (the dominant cost on length-jittered
                    # inputs — one Python CIGAR decode per read otherwise)
                    row_sm = (batch.n_cigar[span_v] == 1) \
                        & ((batch.buf[co[span_v]] & 0xF) == 0)
                    seg_sm = np.zeros(nseg, dtype=bool)
                    seg_sm[nonempty] = np.minimum.reduceat(
                        row_sm.astype(np.uint8),
                        vstarts[:-1][nonempty]).astype(bool)
                    need &= ~seg_sm
            rev8 = ((batch.flag[span_v] & FLAG_REVERSE) != 0).astype(np.uint8)
            mixed = np.zeros(nseg, dtype=bool)
            if nonempty.any():
                mn = np.minimum.reduceat(rev8, vstarts[:-1][nonempty])
                mx = np.maximum.reduceat(rev8, vstarts[:-1][nonempty])
                mixed[nonempty] = (mn == 0) & (mx == 1)
            strand_check = alive & ~need & mixed & (c1 >= 2)
            if strand_check.any():
                # single-op CIGARs simplify to one run: always palindromic
                n1 = batch.n_cigar[span[first_valid]]
                for s in np.nonzero(strand_check & (n1 != 1))[0]:
                    rec_i = int(span[first_valid[s]])
                    cig_bytes = batch.buf[
                        batch.cigar_off[rec_i]:
                        batch.cigar_off[rec_i]
                        + 4 * batch.n_cigar[rec_i]].tobytes()
                    palin = self._palin_cache.get(cig_bytes)
                    if palin is None:
                        cig = cigar_utils.simplify(
                            self._decode_cigar(batch, rec_i))
                        palin = cig == list(reversed(cig))
                        if len(self._palin_cache) >= 4096:
                            self._palin_cache.clear()
                        self._palin_cache[cig_bytes] = palin
                    if not palin:
                        need[s] = True
            legacy_g[seg_g[need]] = True

        vec_g = ~legacy_g
        stats.input_reads += int(sizes[vec_g].sum())
        n_small = int(sizes[small & vec_g].sum())
        if n_small:
            stats.reject("InsufficientReads", n_small)

        seg_map = None
        if nseg:
            seg_vec = vec_g[seg_g]
            dead0 = seg_vec & ~alive0
            if dead0.any():
                stats.reject("InsufficientReads", int(c0[dead0].sum()))
            zl = int((c0 - c1)[seg_vec & alive0].sum())
            if zl:
                stats.reject("ZeroLengthAfterTrimming", zl)
            dead1 = seg_vec & alive0 & ~alive & (c1 > 0)
            if dead1.any():
                stats.reject("InsufficientReads", int(c1[dead1].sum()))

            # consensus length: min_reads-th longest valid len per seg
            ord2 = np.lexsort((-vlens.astype(np.int64), seg_of_row[valid_row]))
            lens_sorted = vlens[ord2]
            pick = np.minimum(vstarts[:-1] + (min_reads - 1),
                              np.maximum(len(lens_sorted) - 1, 0))
            cons_len = (lens_sorted[pick] if len(lens_sorted)
                        else np.zeros(nseg, dtype=vlens.dtype))

            live = alive & seg_vec
            seg_map = np.full((nG, 3), -1, dtype=np.int64)
            seg_map[seg_g[live], seg_t[live]] = np.nonzero(live)[0]
            # orphan R1/R2 rejection, aggregated (vanilla.py:346-357)
            have_r1 = seg_map[:, R1] >= 0
            have_r2 = seg_map[:, R2] >= 0
            lone_r1 = seg_map[:, R1][have_r1 & ~have_r2]
            lone_r2 = seg_map[:, R2][have_r2 & ~have_r1]
            n_orphan = int(c1[lone_r1].sum() + c1[lone_r2].sum())
            if n_orphan:
                stats.reject("OrphanConsensus", n_orphan)

        # legacy groups (downsample / alignment-filter / strand cases): the
        # per-group scan, collected as (order-key, group-start, job-tuple)
        legacy = []
        for g in np.nonzero(legacy_g)[0]:
            jobs_g = []
            self._prepare_group_fast(batch, span, gb[g], gb[g + 1], rtype,
                                     final_len, jobs_g,
                                     bool(group_uniform[g]),
                                     ordinal=ord0 + int(g))
            legacy.extend((int(g) * 3 + i, int(gb[g]), jg)
                          for i, jg in enumerate(jobs_g))

        # vectorized emission: seg_map columns are already in output order
        # (fragment, R1, R2 per group; vanilla.py:377-386), so the row-major
        # flatten index IS the (group, slot) order key
        if nseg:
            flat = seg_map.copy()
            pair = (flat[:, R1] >= 0) & (flat[:, R2] >= 0)
            flat[~pair, R1] = -1
            flat[~pair, R2] = -1
            flat = flat.ravel()
            key_vec = np.nonzero(flat >= 0)[0]
            vseg = flat[key_vec]
        else:
            key_vec = np.empty(0, dtype=np.int64)
            vseg = np.empty(0, dtype=np.int64)
            vrows = np.empty(0, dtype=np.int64)
            span_v = np.empty(0, dtype=np.int64)
            c1 = np.empty(0, dtype=np.int64)
            vstarts = np.zeros(1, dtype=np.int64)
            seg_t = np.empty(0, dtype=np.int8)
            seg_g = np.empty(0, dtype=np.int64)
            cons_len = np.empty(0, dtype=np.int64)

        cnt_v = c1[vseg].astype(np.int64)
        vlo_v = vstarts[:-1][vseg].astype(np.int64)
        typ_v = seg_t[vseg].astype(np.int8)
        len_v = cons_len[vseg].astype(np.int32)
        mi_v = span[gb[seg_g[vseg]]].astype(np.int64)

        if not legacy:
            return _JobTable(cnt_v, vlo_v, typ_v, len_v, mi_v, vrows, span_v)

        nleg = len(legacy)
        cnt_l = np.fromiter((len(jg[1]) for _, _, jg in legacy),
                            np.int64, nleg)
        vlo_l = len(vrows) + np.concatenate(([0], np.cumsum(cnt_l)[:-1]))
        typ_l = np.fromiter((jg[0] for _, _, jg in legacy), np.int8, nleg)
        len_l = np.fromiter((jg[2] for _, _, jg in legacy), np.int32, nleg)
        mi_l = np.fromiter((span[s] for _, s, _ in legacy), np.int64, nleg)
        key_l = np.fromiter((k for k, _, _ in legacy), np.int64, nleg)
        aux = np.concatenate([jg[1] for _, _, jg in legacy])
        order = np.argsort(np.concatenate((key_vec, key_l)), kind="stable")
        return _JobTable(
            np.concatenate((cnt_v, cnt_l))[order],
            np.concatenate((vlo_v, vlo_l))[order],
            np.concatenate((typ_v, typ_l))[order],
            np.concatenate((len_v, len_l))[order],
            np.concatenate((mi_v, mi_l))[order],
            np.concatenate((vrows, aux)),
            np.concatenate((span_v, span[aux])))

    def _prepare_group_fast(self, batch, span, s, e, rtype, final_len, jobs,
                            group_uniform=False, ordinal=None):
        """prepare_group analog on array slices (vanilla.py:274-357).

        `ordinal` is the group's downsample-RNG ordinal; None allocates the
        next one (the vectorized path pre-allocates a span's worth and passes
        each group's explicitly)."""
        caller = self.caller
        opts = caller.options
        stats = caller.stats
        n_records = e - s
        stats.input_reads += int(n_records)
        if ordinal is None:
            ordinal = caller._group_ordinal
            caller._group_ordinal += 1

        def rej(rows_arr):
            # rejects materialize as RawRecords only when tracking is on
            if caller.track_rejects and len(rows_arr):
                caller.rejected_reads.extend(batch.raw_records(span[rows_arr]))

        # secondary/supplementary were pre-filtered from idx; prepare_group's
        # first filter is a no-op here, so `reads` == all group records
        if n_records < opts.min_reads:
            stats.reject("InsufficientReads", int(n_records))
            rej(np.arange(s, e))
            return

        rows = np.arange(s, e)
        if opts.max_reads is not None and n_records > opts.max_reads:
            rng = np.random.Generator(
                np.random.Philox(key=(opts.seed or 0) + ordinal))
            perm = rng.permutation(n_records)[:opts.max_reads]
            rows = rows[perm]  # permuted order, like _downsample

        group_jobs = {}
        for read_type in (FRAGMENT, R1, R2):
            t_rows = rows[rtype[rows] == read_type]
            if len(t_rows) == 0:
                continue
            if len(t_rows) < opts.min_reads:
                stats.reject("InsufficientReads", int(len(t_rows)))
                rej(t_rows)
                continue
            lens = final_len[t_rows]
            ok = lens > 0
            zero_len = int((~ok).sum())
            if zero_len:
                stats.reject("ZeroLengthAfterTrimming", zero_len)
                rej(t_rows[~ok])
                t_rows = t_rows[ok]
                lens = lens[ok]
            if len(t_rows) < opts.min_reads:
                if len(t_rows):
                    stats.reject("InsufficientReads", int(len(t_rows)))
                    rej(t_rows)
                continue
            # most-common-alignment filter (vanilla.py:210-222): identical
            # simplified CIGARs always form a single compatibility group ->
            # keep all. Identical raw bytes imply that only when strands agree
            # or the simplified CIGAR is palindromic (reverse-strand reads use
            # the reversed simplified CIGAR, vanilla.py:199-201).
            if group_uniform:
                need_filter = False
            else:
                cig_off = np.ascontiguousarray(batch.cigar_off[span[t_rows]])
                cig_len = (4 * batch.n_cigar[span[t_rows]]).astype(np.int32)
                runs = nb.group_starts(batch.buf, cig_off, cig_len)
                need_filter = len(runs) > 1
                if need_filter \
                        and (batch.n_cigar[span[t_rows]] == 1).all() \
                        and ((batch.buf[cig_off] & 0xF) == 0).all():
                    # all-single-op-M: mutually prefix-compatible, the
                    # filter keeps everything (see _prepare_groups_vec)
                    need_filter = False
            if not need_filter and len(t_rows) >= 2:
                revs = (batch.flag[span[t_rows]] & FLAG_REVERSE) != 0
                if revs.any() and not revs.all():
                    cig = cigar_utils.simplify(
                        self._decode_cigar(batch, int(span[t_rows[0]])))
                    need_filter = cig != list(reversed(cig))
            if need_filter:
                keep_rows = self._alignment_filter(batch, span, t_rows, lens)
                rejected = len(t_rows) - len(keep_rows)
                if rejected:
                    stats.reject("MinorityAlignment", rejected)
                    keep_set = set(keep_rows.tolist())
                    rej(np.array([r for r in t_rows if r not in keep_set],
                                 dtype=np.int64))
                t_rows = keep_rows
                lens = final_len[t_rows]
                if len(t_rows) < opts.min_reads:
                    if len(t_rows):
                        stats.reject("InsufficientReads", int(len(t_rows)))
                        rej(t_rows)
                    continue
            lens_sorted = np.sort(lens)[::-1]
            consensus_len = int(lens_sorted[opts.min_reads - 1])
            group_jobs[read_type] = (read_type, t_rows, consensus_len)

        # orphan R1/R2 handling (vanilla.py:346-357)
        if FRAGMENT in group_jobs:
            jobs.append(group_jobs[FRAGMENT])
        r1, r2 = group_jobs.get(R1), group_jobs.get(R2)
        if r1 is not None and r2 is not None:
            jobs.extend([r1, r2])
        elif r1 is not None:
            stats.reject("OrphanConsensus", len(r1[1]))
            rej(r1[1])
        elif r2 is not None:
            stats.reject("OrphanConsensus", len(r2[1]))
            rej(r2[1])

    def _alignment_filter(self, batch, span, t_rows, lens):
        """Non-uniform CIGARs: decode + simplify + truncate per read, then the
        exact fgbio filter (cigar_utils.select_most_common_alignment_group)."""
        entries = []
        for local, (row, ln) in enumerate(zip(t_rows, lens)):
            rec_i = int(span[row])
            cig = self._decode_cigar(batch, rec_i)
            simplified = cigar_utils.simplify(cig)
            if batch.flag[rec_i] & FLAG_REVERSE:
                simplified = cigar_utils.reverse(simplified)
            simplified = cigar_utils.truncate_to_query_length(
                simplified, int(ln))
            entries.append((local, int(ln), simplified))
        entries.sort(key=lambda t: -t[1])
        keep = cigar_utils.select_most_common_alignment_group(entries)
        keep_set = set(keep)
        return t_rows[[local in keep_set for local in range(len(t_rows))]]

    @staticmethod
    def _decode_cigar(batch, rec_i):
        off = batch.cigar_off[rec_i]
        n = batch.n_cigar[rec_i]
        # tobytes() realigns: a uint32 view of an odd-offset slice would fail
        raw = np.frombuffer(batch.buf[off: off + 4 * n].tobytes(),
                            dtype="<u4")
        return [(_CIGAR_OPS[v & 0xF], int(v) >> 4) for v in raw]

    # ------------------------------------------------------------------ device

    def _dispatch_jobs(self, codes, quals, table):
        """One dense segment-sum kernel dispatch for the whole batch.

        Single-read jobs run vectorized on host (one (S, L) gather + table
        lookup); multi-read jobs concatenate their packed read rows into a
        dense (N, L) layout with sorted segment ids — one device execution
        and one uint16 fetch per record batch, independent of family-size
        mix (per-execution relay overhead dominates the compute on the
        tunnel-attached device). The fetch + threshold + serialize half runs
        in _PendingChunk.resolve() (SURVEY §7 step 4: host prep overlaps
        device compute and transfer). Returns (pending-or-None, host_blocks).
        """
        caller = self.caller
        opts = caller.options
        kernel = caller.kernel
        count = table.count
        blocks0 = []

        single = np.nonzero(count == 1)[0]
        if len(single):
            rows1 = table.pool_rows[table.vlo[single]]
            Lm = int(table.cons_len[single].max())
            b, q, d, e = oracle.single_read_consensus(
                codes[rows1, :Lm], quals[rows1, :Lm], caller.tables,
                opts.min_consensus_base_quality)
            blocks0.append((single, np.ascontiguousarray(b),
                            np.ascontiguousarray(q),
                            np.ascontiguousarray(d.astype(np.int32)),
                            np.ascontiguousarray(e.astype(np.int32))))

        multi = np.nonzero(count > 1)[0]
        if len(multi) == 0:
            return None, blocks0

        counts = count[multi]
        rows_all = table.pool_rows[_ranges(table.vlo[multi], counts)]
        # 4-multiple L >= every job's consensus length (<= the pack stride);
        # 4 (not 16) because every padded position is an uploaded wire byte
        # and the 2-bit winner output packs 4 positions per byte
        L_max = -(-int(table.cons_len[multi].max()) // 4) * 4

        from ..ops.kernel import HOST_DISPATCH, device_path
        from ..ops.router import ROUTER

        N = len(rows_all)
        mesh = self.mesh
        # full-column gate (uint16 depth fetch) decided BEFORE routing so
        # the fused-filter pricing below can never be promised for a batch
        # that would actually dispatch the ordinary full-column kernel
        full = bool(counts.max() < 65536)
        fused_filter = False
        if self.filter_stage is not None and mesh is None and full:
            from .device_filter import device_mask_enabled

            fused_filter = device_mask_enabled() and device_path() == "full"
        if kernel.host_mode():
            side = "host"
        else:
            # adaptive offload: price this batch on both sides from
            # measured EWMAs (ops/router.py decide_batch) — the mesh size
            # selects its own EWMA set, so an N-chip device side is priced
            # as N chips, not as the single-device model. A fused-filter
            # batch is priced with its reduced fetch (stats row + keep-rate
            # scaled survivor columns) instead of the full-column fetch.
            side = ROUTER.decide_batch(
                kernel, N, len(multi), L_max,
                devices=mesh.size if mesh is not None else 1,
                filtered=fused_filter)
        if side == "host":
            # host f64 engine path: either no device at all, or the cost
            # model priced this batch host-side — the native engine eats it
            # CONCURRENTLY on the resolve pool, so e2e throughput is
            # device + host, not min of the two. No pad, no device layout:
            # the native engine consumes ragged rows.
            starts = np.concatenate(([0], np.cumsum(counts)))
            return ("seg", multi, starts,
                    np.ascontiguousarray(codes[rows_all, :L_max]),
                    np.ascontiguousarray(quals[rows_all, :L_max]),
                    HOST_DISPATCH), blocks0

        if device_path() == "columns":
            # round-5 comparison route (FGUMI_TPU_DEVICE_PATH=columns):
            # native classify resolves the easy columns on host; only the
            # hard few percent cross the link as a compact observation
            # stream (ops/kernel.py dispatch_hard_columns)
            starts = np.concatenate(([0], np.cumsum(counts)))
            pending = kernel.dispatch_hard_columns(
                np.ascontiguousarray(codes[rows_all, :L_max]),
                np.ascontiguousarray(quals[rows_all, :L_max]), starts)
            return ("cols", multi, pending), blocks0

        # full-column device route (the round-6 default): the whole batch
        # crosses the link once in the 1 B/position wire layout and the
        # device resolves every column — winner/qual/depth/errors per
        # position, no host re-walk of the dense rows at resolve time.
        # With a > 1-device mesh the same wire kernels run shard_map-
        # wrapped over (dp, sp) (ops/kernel.pad_segments_mesh +
        # _dispatch_wire_mesh); resolve is the identical "segw" pending —
        # byte-identity with the single-device path is the test oracle.
        import time

        from ..ops.kernel import pad_segments_gather, pad_segments_mesh

        t_pack0 = time.monotonic()  # gather+pad+wire == this batch's pack
        pred = ROUTER.last_prediction()
        if mesh is not None:
            codes_d = np.ascontiguousarray(codes[rows_all, :L_max])
            quals_d = np.ascontiguousarray(quals[rows_all, :L_max])
            codes_g, quals_g, seg_g, starts_p, F_loc, gather = \
                pad_segments_mesh(codes_d, quals_d, counts, mesh)
            ticket = kernel.device_call_segments_wire(
                codes_g, quals_g, seg_g, F_loc, len(multi),
                pack_t0=t_pack0, full=full,
                pred_s=pred[0] if pred else None, mesh=mesh,
                mesh_gather=gather)
            return ("segw", multi, starts_p, codes_d, quals_d,
                    ticket), blocks0
        codes_dev, quals_dev, seg_ids, starts_p, F_pad, N_real = \
            pad_segments_gather(codes, quals, rows_all, L_max, counts)
        if fused_filter:
            # fused consensus→filter dispatch: per-read stats fetch +
            # device-resident masked columns (survivors gathered at
            # resolve time by the filter stage)
            ticket = kernel.device_call_segments_wire(
                codes_dev, quals_dev, seg_ids, F_pad, len(multi),
                pack_t0=t_pack0, full=True,
                pred_s=pred[0] if pred else None,
                filter_params=(
                    np.int32(opts.min_reads),
                    np.int32(opts.min_consensus_base_quality),
                    table.cons_len[multi].astype(np.int32),
                    self.filter_stage.dev_params))
            return ("segwf", multi, starts_p, codes_dev[:N_real],
                    quals_dev[:N_real], ticket), blocks0
        ticket = kernel.device_call_segments_wire(
            codes_dev, quals_dev, seg_ids, F_pad, len(multi),
            pack_t0=t_pack0, full=full,
            pred_s=pred[0] if pred else None)
        return ("segw", multi, starts_p, codes_dev[:N_real],
                quals_dev[:N_real], ticket), blocks0

    # ------------------------------------------------------------------ output

    def _serialize_jobs(self, batch, table, blocks=()) -> bytes:
        """Native batch serializer: all jobs -> one block_size-prefixed wire
        blob (fgumi_build_consensus_records; _build_record semantics).
        `blocks` carries every job's result rows as whole blocks (addresses
        computed per block); MI bytes resolve to pointers straight into the
        batch buffer (table.mi_rec), no per-job copies."""
        caller = self.caller
        opts = caller.options
        J = len(table)
        lens = np.ascontiguousarray(table.cons_len, dtype=np.int32)
        flags = _TYPE_FLAGS_ARR[table.read_type]
        code_addr = np.empty(J, dtype=np.int64)
        qual_addr = np.empty(J, dtype=np.int64)
        depth_addr = np.empty(J, dtype=np.int64)
        err_addr = np.empty(J, dtype=np.int64)
        keep_alive = []
        for idxs, b, q, d, e in blocks:
            keep_alive.append((b, q, d, e))
            fi = np.arange(len(idxs), dtype=np.int64)
            code_addr[idxs] = b.ctypes.data + fi * b.shape[1]
            qual_addr[idxs] = q.ctypes.data + fi * q.shape[1]
            depth_addr[idxs] = d.ctypes.data + fi * (4 * d.shape[1])
            err_addr[idxs] = e.ctypes.data + fi * (4 * e.shape[1])

        buf = batch.buf
        buf_base = buf.ctypes.data
        mi_vo, mi_vl, _ = batch.tag_locs(self.tag)
        mi_addr = np.ascontiguousarray(buf_base + mi_vo[table.mi_rec],
                                       dtype=np.int64)
        mi_len = np.ascontiguousarray(mi_vl[table.mi_rec], dtype=np.int32)

        # consensus RX from the surviving reads' RX tags (vanilla.py:460-464):
        # unanimity (the overwhelmingly common case) resolves natively to a
        # pointer into the batch buffer; only divergent families run the
        # Python likelihood consensus
        rx_vo, rx_vl, _ = batch.tag_locs_str(b"RX")
        surv_counts = table.count
        surv_starts = np.concatenate(([0], np.cumsum(surv_counts)))
        surv_all = table.pool_span[_ranges(table.vlo, surv_counts)]
        rxo, rxl = nb.rx_unanimous(buf, rx_vo[surv_all], rx_vl[surv_all],
                                   surv_starts)
        rx_addr = np.where(rxo >= 0, buf_base + rxo, 0)
        rx_len = np.where(rxo >= 0, rxl, 0).astype(np.int32)
        divergent = np.nonzero(rxo == -2)[0]
        if len(divergent):
            fams = []
            for j in divergent:
                lo = int(table.vlo[j])
                hi = lo + int(table.count[j])
                fams.append(
                    [buf[rx_vo[i]: rx_vo[i] + rx_vl[i]].tobytes().decode()
                     for i in table.pool_span[lo:hi] if rx_vo[i] >= 0])
            for j, rx in zip(divergent, consensus_umis_batch(fams)):
                rx_arr = np.frombuffer(rx.encode(), dtype=np.uint8)
                keep_alive.append(rx_arr)
                rx_addr[j] = rx_arr.ctypes.data
                rx_len[j] = len(rx_arr)

        blob, _ = nb.build_consensus_records(
            code_addr, qual_addr, depth_addr, err_addr, lens, flags,
            caller.prefix.encode(), mi_addr, mi_len, rx_addr, rx_len,
            caller.read_group_id.encode(), opts.produce_per_base_tags)
        caller.stats.add_consensus_reads(J)
        del keep_alive
        return blob


_CIGAR_OPS = "MIDNSHP=X"


def overlap_correct_span(batch, idx, bounds, g0, g1, oc):
    """In-place R1/R2 overlap correction over groups [g0, g1) of `idx`.

    Pairs primary R1/R2 by name within each group; one native call. Shared by
    the fast simplex engine (MI groups) and the fast duplex engine
    ((molecule, strand) subgroups).
    """
    flag = batch.flag
    span = idx[bounds[g0]:bounds[g1]]
    # fast path: the grouped-BAM layout keeps each template's primary R1
    # immediately followed by its R2 (group output preserves template
    # adjacency); vectorized detection of (FIRST, LAST) runs with equal
    # names covers it, the per-group dict pairing is the general fallback
    f_span = flag[span]
    # candidate adjacency: FIRST record followed by a LAST-and-not-FIRST
    # one (a FIRST|LAST record sorts into the R1 slot in the dict/
    # reference pairing, overlapping.py:203-206, and never completes a
    # pair — it must not complete one here either)
    is_first = (f_span[:-1] & FLAG_FIRST) != 0
    next_last = ((f_span[1:] & FLAG_LAST) != 0) \
        & ((f_span[1:] & FLAG_FIRST) == 0)
    cand = np.nonzero(is_first & next_last)[0]
    # a pair must not straddle an MI-group boundary: the dict pairing is
    # per group, so a FIRST ending group g adjacent to a LAST opening
    # group g+1 (same-name duplicates across groups in a malformed BAM)
    # must stay two orphans, not become a cross-family correction
    if len(cand) and g1 - g0 > 1:
        boundary = np.zeros(len(span) + 1, dtype=bool)
        boundary[bounds[g0 + 1:g1] - bounds[g0]] = True
        cand = cand[~boundary[cand + 1]]
    adjacent_ok = False
    # flag-level completeness precheck (no name comparisons): every
    # FIRST/LAST-flagged record must sit in some candidate adjacency,
    # else an orphan exists somewhere and the dict scan runs anyway
    first_or_last = (f_span & (FLAG_FIRST | FLAG_LAST)) != 0
    if len(cand):
        # candidates are never adjacent: cand i requires row i+1 to be
        # LAST-and-not-FIRST while cand i+1 would require that same row to
        # be FIRST — so every candidate pair is conflict-free and the
        # greedy keep reduces to the whole candidate set (vectorized; this
        # was a 184k-iteration Python loop per run)
        used = np.zeros(len(span), dtype=bool)
        used[cand] = True
        used[cand + 1] = True
        if bool(used[first_or_last].all()):
            keep = cand
            a, b = span[keep], span[keep + 1]
            name_off = batch.data_off + 32
            name_len = (batch.l_read_name - 1).astype(np.int32)
            same = nb.ranges_equal(batch.buf, name_off[a], name_len[a],
                                   name_off[b], name_len[b])
            # repeated names among kept pairs diverge from the dict
            # pairing (last-writer-wins slots correct only one pair);
            # hash-collision false positives only cause a safe fallback
            hashes = nb.hash_ranges(batch.buf, name_off[a], name_len[a])
            if same.all() and len(np.unique(hashes)) == len(hashes):
                adjacent_ok = True
                r1_offs = batch.data_off[a]
                r2_offs = batch.data_off[b]
    if not adjacent_ok:
        # vectorized (group, name-hash) pairing: keys with exactly one
        # FIRST and one LAST row pair directly (names confirmed by one
        # batched ranges_equal — a hash collision or any odd key shape
        # sends just that group to the per-record dict pairing, whose
        # last-writer-wins semantics stay the reference for weird inputs)
        r1_offs = []
        r2_offs = []
        bad_groups = set()
        rel_bounds = bounds[g0:g1 + 1] - bounds[g0]
        g_of = np.repeat(np.arange(g1 - g0), np.diff(rel_bounds))
        fl_first = (f_span & FLAG_FIRST) != 0
        fl_last = ((f_span & FLAG_LAST) != 0) & ~fl_first
        rid = np.nonzero(fl_first | fl_last)[0]
        if len(rid):
            name_off_s = batch.data_off[span[rid]] + 32
            name_len_s = (batch.l_read_name[span[rid]] - 1).astype(np.int32)
            h = nb.hash_ranges(batch.buf, name_off_s, name_len_s)
            o = np.lexsort((h, g_of[rid]))
            gg, hh = g_of[rid][o], h[o]
            newkey = np.concatenate(
                ([True], (gg[1:] != gg[:-1]) | (hh[1:] != hh[:-1])))
            kb = np.nonzero(np.concatenate((newkey, [True])))[0]
            sizes = np.diff(kb)
            two = np.nonzero(sizes == 2)[0]
            big = np.nonzero(sizes > 2)[0]
            if len(big):
                bad_groups.update(np.unique(gg[kb[big]]).tolist())
            if len(two):
                ra = rid[o[kb[two]]]
                rb = rid[o[kb[two] + 1]]
                one_first = fl_first[ra] ^ fl_first[rb]
                # orient: FIRST -> a slot, LAST -> b slot
                swap = ~fl_first[ra]
                ra2 = np.where(swap, rb, ra)
                rb2 = np.where(swap, ra, rb)
                a_rows = span[ra2]
                b_rows = span[rb2]
                same_name = nb.ranges_equal(
                    batch.buf, batch.data_off[a_rows] + 32,
                    (batch.l_read_name[a_rows] - 1).astype(np.int32),
                    batch.data_off[b_rows] + 32,
                    (batch.l_read_name[b_rows] - 1).astype(np.int32)
                ).astype(bool)
                ok = one_first & same_name
                pair_g = g_of[ra]
                bad_groups.update(np.unique(pair_g[~ok]).tolist())
                # a bad group's rows pair in the dict fallback below —
                # keeping its vectorized pairs would correct them twice
                if bad_groups:
                    bad_arr = np.fromiter(bad_groups, dtype=np.int64,
                                          count=len(bad_groups))
                    ok &= ~np.isin(pair_g, bad_arr)
                r1_offs = batch.data_off[a_rows[ok]]
                r2_offs = batch.data_off[b_rows[ok]]
        if bad_groups:
            extra_a = []
            extra_b = []
            for g_rel in sorted(bad_groups):
                g = g0 + int(g_rel)
                members = idx[bounds[g]:bounds[g + 1]]
                pairs = {}
                for i in members:
                    f = int(flag[i])
                    # secondary/supplementary were already filtered from idx
                    slot = pairs.setdefault(batch.name(int(i)), [None, None])
                    if f & FLAG_FIRST:
                        slot[0] = int(i)
                    elif f & FLAG_LAST:
                        slot[1] = int(i)
                for a, b in pairs.values():
                    if a is not None and b is not None:
                        extra_a.append(batch.data_off[a])
                        extra_b.append(batch.data_off[b])
            r1_offs = np.concatenate(
                [np.asarray(r1_offs, dtype=np.int64),
                 np.asarray(extra_a, dtype=np.int64)])
            r2_offs = np.concatenate(
                [np.asarray(r2_offs, dtype=np.int64),
                 np.asarray(extra_b, dtype=np.int64)])
    if len(r1_offs) == 0:
        return
    stats = nb.overlap_correct_pairs(
        batch.buf, np.asarray(r1_offs, dtype=np.int64),
        np.asarray(r2_offs, dtype=np.int64),
        AGREEMENT_CODES[oc.agreement], DISAGREEMENT_CODES[oc.disagreement])
    add_native_overlap_stats(oc.stats, stats)
