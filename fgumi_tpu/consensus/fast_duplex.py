"""Vectorized duplex consensus path over RecordBatch inputs.

The duplex analog of consensus/fast.py: per-record work happens natively in
batch (fgumi_tpu.native.batch), per-molecule work on numpy index slices, the
single-strand likelihood loop on the device kernel, stage-2 strand
combination as whole-batch array math, and record serialization in one
native call (fgumi_build_duplex_records).

Semantics contract: byte-identical output and identical rejection statistics
to DuplexConsensusCaller.call_groups on the same stream (reference
duplex_caller.rs:1755-2268; tested in tests/test_fast_duplex.py). Molecules
the vectorized path cannot express (FIRST|LAST-flagged reads, per-strand
downsampling, most-common-alignment filtering) fall back to the slow caller
per molecule, in stream order.
"""

import numpy as np

from ..constants import MAX_PHRED, MIN_PHRED, N_CODE
from ..io.bam import (FLAG_FIRST, FLAG_LAST, FLAG_MATE_UNMAPPED, FLAG_PAIRED,
                      FLAG_REVERSE, FLAG_SECONDARY, FLAG_SUPPLEMENTARY,
                      FLAG_UNMAPPED)
from ..native import batch as nb
from ..ops import oracle
from .fast import overlap_correct_span
from .simple_umi import _ACGTN_UPPER, consensus_umis_batch
from .vanilla import I16_MAX, R1, R2, _TYPE_FLAGS

# seg types within a molecule: (strand, read-type) -> 0..3
AB_R1, AB_R2, BA_R1, BA_R2 = 0, 1, 2, 3


def _flip_umi(value: str) -> str:
    """Dual-UMI strand reorientation (duplex_caller.rs:1226-1231)."""
    return "-".join(reversed(value.split("-")))


class _DuplexPending:
    """Deferred half of a duplex batch: the SS device fetch + stage-2
    combine + serialization run at resolve time (pipeline.resolve_chunk),
    after the NEXT batch's dispatch is in flight."""

    __slots__ = ("_fn",)

    def __init__(self, fn):
        self._fn = fn

    def resolve(self) -> bytes:
        return self._fn()


class FastDuplexCaller:
    """Batch-vectorized duplex caller wrapping a DuplexConsensusCaller.

    The wrapped caller owns options/stats/kernel and serves as the
    per-molecule fallback, so statistics and output bytes are shared across
    both paths.
    """

    def __init__(self, caller, tag: bytes = b"MI", overlap_caller=None,
                 mesh=None):
        """`mesh`: optional jax Mesh with (dp, sp) axes — multi-read SS
        segments dispatch through the shard_map-wrapped full-column wire
        kernels (same mesh compile path as the simplex caller, including
        the resident fused strand combine). None or a 1-device mesh = the
        legacy single-device path, bit for bit."""
        self.caller = caller
        self.ss = caller.ss
        self.kernel = caller.ss.kernel
        self.tag = tag
        self.overlap_caller = overlap_caller
        self.mesh = mesh if mesh is not None and mesh.size > 1 else None
        # device/host routing is per batch via the adaptive cost model
        # (ops/router.py; FGUMI_TPU_ROUTE / FGUMI_TPU_MAX_INFLIGHT handled
        # inside ROUTER.decide)
        self._carry = None  # (base_mi, [RawRecord] a, [RawRecord] b)
        # With threads<=1 the CLI sets this True: the SS device round trip is
        # then deferred into a pending chunk resolved AFTER the next batch's
        # dispatch (pipeline.run_stages double buffering), hiding the fetch
        # behind host prep. Ordinals are pre-reserved at process time so MI
        # numbering is identical either way. Must stay False when resolve_fn
        # runs on another thread: stage-2 mutates shared stats/ordinals.
        self.defer_device = False

    # ------------------------------------------------------------------ driver

    def process_batch(self, batch, allow_unmapped: bool = False,
                      final: bool = False):
        """Consume one RecordBatch -> list of wire chunks (block_size-prefixed
        record runs). The molecule spanning the batch boundary is carried as
        RawRecords and processed via the slow path when it completes."""
        flag = batch.flag
        keep = (flag & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY)) == 0
        if not allow_unmapped:
            is_mapped = (flag & FLAG_UNMAPPED) == 0
            mapped_mate = ((flag & FLAG_PAIRED) != 0) \
                & ((flag & FLAG_MATE_UNMAPPED) == 0)
            keep &= is_mapped | mapped_mate
        idx = np.nonzero(keep)[0]
        if len(idx) == 0:
            return self.flush() if final else []

        batch.prefetch_tags([self.tag, b"MC", b"RX"])
        mi_off, mi_len, _ = batch.tag_locs(self.tag)
        mo, ml = mi_off[idx], mi_len[idx]
        if (mo < 0).any():
            bad = int(idx[np.nonzero(mo < 0)[0][0]])
            raise ValueError(
                f"record {batch.name(bad)!r} missing {self.tag.decode()} tag")
        buf = batch.buf
        ok = (ml >= 3) & (buf[mo + ml - 2] == ord("/")) \
            & ((buf[mo + ml - 1] == ord("A")) | (buf[mo + ml - 1] == ord("B")))
        if not ok.all():
            bad = int(idx[np.nonzero(~ok)[0][0]])
            mi = batch.tag_bytes(self.tag, bad).decode()
            raise ValueError(
                f"Read has MI tag {mi!r} without /A or /B suffix. Duplex "
                "consensus requires input from `group --strategy paired`, "
                "which marks the source strand.")

        starts = nb.group_starts(buf, np.ascontiguousarray(mo),
                                 (ml - 2).astype(np.int32))
        bounds = np.append(starts, len(idx))
        n_total = len(bounds) - 1
        strand_b = buf[mo + ml - 1] == ord("B")  # per kept row

        def materialize(lo, hi):
            rows = idx[lo:hi]
            a = batch.raw_records(rows[~strand_b[lo:hi]])
            b = batch.raw_records(rows[strand_b[lo:hi]])
            return a, b

        first_base = self._base_mi(batch, int(idx[bounds[0]]))
        merge_carry = self._carry is not None and self._carry[0] == first_base
        if merge_carry:
            a, b = materialize(bounds[0], bounds[1])
            self._carry[1].extend(a)
            self._carry[2].extend(b)

        g0 = 1 if merge_carry else 0
        g1 = n_total if final else max(n_total - 1, g0)
        deferred = None
        if not final and n_total - 1 >= g0:
            a, b = materialize(bounds[n_total - 1], bounds[n_total])
            deferred = (self._base_mi(batch, int(idx[bounds[n_total - 1]])),
                        a, b)

        out = []
        if self._carry is not None:
            if (not merge_carry) or final or n_total >= 2:
                out.extend(self._call_slow_molecule(*self._carry))
                self._carry = None

        if g1 > g0:
            if self.overlap_caller is not None:
                self._overlap_correct(batch, idx, bounds, strand_b, g0, g1)
            out.extend(self._process_molecules(batch, idx, bounds, strand_b,
                                               g0, g1))

        if deferred is not None:
            self._carry = deferred
        if final:
            out.extend(self.flush())
        return out

    def flush(self):
        if self._carry is None:
            return []
        base_mi, a, b = self._carry
        self._carry = None
        return self._call_slow_molecule(base_mi, a, b)

    def _base_mi(self, batch, i: int) -> str:
        return batch.tag_bytes(self.tag, i)[:-2].decode()

    # ------------------------------------------------------------ slow interop

    def _call_slow_molecule(self, base_mi, a_records, b_records,
                            corrected=False):
        """One molecule through DuplexConsensusCaller (the semantic
        reference). Overlap correction applies here unless the records were
        already corrected in place natively."""
        if self.overlap_caller is not None and not corrected \
                and a_records and b_records:
            from .overlapping import apply_overlapping_consensus

            a_records = apply_overlapping_consensus(a_records,
                                                    self.overlap_caller)
            b_records = apply_overlapping_consensus(b_records,
                                                    self.overlap_caller)
        recs = self.caller.call_groups([(base_mi, a_records, b_records)])
        if not recs:
            return []
        return [b"".join(len(r).to_bytes(4, "little") + r for r in recs)]

    # ------------------------------------------------------------ overlap corr

    def _overlap_correct(self, batch, idx, bounds, strand_b, g0, g1):
        """Per (molecule, strand) correction for molecules with both strands
        (the cmd-level `a_recs and b_recs` gate, duplex.rs has_both_strands)."""
        nG = g1 - g0
        lo, hi = bounds[g0], bounds[g1]
        g_of_row = np.repeat(np.arange(nG), np.diff(bounds[g0:g1 + 1]))
        sb = strand_b[lo:hi]
        n_b = np.bincount(g_of_row, weights=sb, minlength=nG)
        n_a = np.bincount(g_of_row, weights=~sb, minlength=nG)
        both = (n_a > 0) & (n_b > 0)
        if not both.any():
            return
        rows_ok = both[g_of_row]
        er = np.nonzero(rows_ok)[0]
        key = g_of_row[er] * 2 + sb[er]
        order = np.argsort(key, kind="stable")
        idx2 = idx[lo:hi][er[order]]
        skey = key[order]
        seg_first = np.concatenate(([True], skey[1:] != skey[:-1]))
        bounds2 = np.append(np.nonzero(seg_first)[0], len(idx2))
        overlap_correct_span(batch, idx2, bounds2, 0, len(bounds2) - 1,
                             self.overlap_caller)

    # ------------------------------------------------------------- stage 1 + 2

    def _process_molecules(self, batch, idx, bounds, strand_b, g0, g1):
        caller = self.caller
        stats = caller.stats
        span = idx[bounds[g0]:bounds[g1]]
        nG = g1 - g0
        gb = bounds[g0:g1 + 1] - bounds[g0]
        sizes = np.diff(gb)
        g_of_row = np.repeat(np.arange(nG), sizes)
        sb = strand_b[bounds[g0]:bounds[g1]]

        flag_s = batch.flag[span]
        paired = (flag_s & FLAG_PAIRED) != 0
        first = (flag_s & FLAG_FIRST) != 0
        last = (flag_s & FLAG_LAST) != 0

        # molecule-level fallback: FIRST|LAST reads (belong to both X and Y
        # sets) and per-strand downsampling
        fallback = np.zeros(nG, dtype=bool)
        fl_both = paired & first & last
        fallback[g_of_row[fl_both]] = True
        max_rs = self.ss.options.max_reads
        if self.caller.track_rejects or self.ss.options.methylation_mode:
            # methylation needs each read's CIGAR/position context for the
            # reference annotation — the packed batch path strips it, so
            # every molecule runs the classic per-molecule path (the same
            # engineering choice as the simplex engine's _vector_ok gate)
            fallback[:] = True

        # per-row seg type (AB_R1..BA_R2); fragments and paired-but-neither
        # get -1
        t = np.full(len(span), -1, dtype=np.int8)
        r1 = paired & first
        r2 = paired & last & ~first
        t[~sb & r1] = AB_R1
        t[~sb & r2] = AB_R2
        t[sb & r1] = BA_R1
        t[sb & r2] = BA_R2

        frag = ~paired
        n_frag = np.bincount(g_of_row[frag], minlength=nG)
        n_paired = sizes - n_frag
        num_a_r1 = np.bincount(g_of_row[~sb & r1], minlength=nG)
        num_b_r1 = np.bincount(g_of_row[sb & r1], minlength=nG)
        num_xy = np.maximum(num_a_r1, num_b_r1)
        num_yx = np.minimum(num_a_r1, num_b_r1)
        gate_ok = (caller.min_total <= num_xy + num_yx) \
            & (caller.min_xy <= num_xy) & (caller.min_yx <= num_yx)

        # strand-orientation validation (duplex_caller.rs:1830-1860): only for
        # molecules with paired rows on both strands; X = AB-R1 + BA-R2 and
        # Y = AB-R2 + BA-R1 must each be strand-uniform
        n_pa = np.bincount(g_of_row[~sb & paired], minlength=nG)
        n_pb = np.bincount(g_of_row[sb & paired], minlength=nG)
        both_strands = (n_pa > 0) & (n_pb > 0)
        rev = (flag_s & FLAG_REVERSE) != 0
        is_x = (t == AB_R1) | (t == BA_R2)
        is_y = (t == AB_R2) | (t == BA_R1)
        coll = np.zeros(nG, dtype=bool)
        for setm in (is_x, is_y):
            gr = g_of_row[setm]
            rv = rev[setm]
            mn = np.full(nG, 2, dtype=np.int8)
            mx = np.full(nG, -1, dtype=np.int8)
            np.minimum.at(mn, gr, rv.astype(np.int8))
            np.maximum.at(mx, gr, rv.astype(np.int8))
            coll |= (mx - mn) > 0
        coll &= both_strands

        # native pack over all rows (clip/trim/RC/mask; fast.py discipline)
        mc_off, mc_len, _ = batch.tag_locs_str(b"MC")
        clips = nb.mate_clips(
            batch.buf, np.ascontiguousarray(batch.cigar_off[span]),
            batch.n_cigar[span], batch.flag[span], batch.ref_id[span],
            batch.pos[span], batch.next_ref_id[span], batch.next_pos[span],
            batch.tlen[span], np.ascontiguousarray(mc_off[span]),
            mc_len[span])
        stride = max(-(-int(batch.l_seq[span].max()) // 32) * 32, 32)
        codes, quals, final_len = nb.pack_reads(
            batch.buf, np.ascontiguousarray(batch.seq_off[span]),
            np.ascontiguousarray(batch.qual_off[span]), batch.l_seq[span],
            rev.astype(np.uint8), clips,
            self.ss.options.min_input_base_quality, stride)

        # seg construction over valid rows of live molecules (dead molecules
        # -- failed gates/validation -- need no conversion at all)
        live_mol = gate_ok & ~coll & (n_paired > 0) & ~fallback
        valid = (final_len > 0) & (t >= 0) & live_mol[g_of_row]
        er = np.nonzero(valid)[0]
        key = g_of_row[er] * 4 + t[er]
        order = np.argsort(key, kind="stable")
        vrows = er[order]
        skey = key[order]
        seg_first = np.concatenate(([True], skey[1:] != skey[:-1])) \
            if len(skey) else np.empty(0, dtype=bool)
        seg_of_row = (np.cumsum(seg_first) - 1) if len(skey) else skey
        seg_key = skey[seg_first] if len(skey) else skey
        nseg = len(seg_key)
        seg_g = seg_key >> 2
        seg_t = (seg_key & 3).astype(np.int8)
        c1 = np.bincount(seg_of_row, minlength=nseg).astype(np.int64)
        vstarts = np.concatenate(([0], np.cumsum(c1))).astype(np.int64)
        if max_rs is not None and nseg and (c1 > max_rs).any():
            fallback[seg_g[c1 > max_rs]] = True

        # alignment-filter analysis per X/Y set of each live molecule:
        # uniform CIGARs over the set's valid rows, with the mixed-strand
        # palindrome rule (fast.py _prepare_groups_vec)
        if nseg:
            self._need_filter_fallback(batch, span, vrows, g_of_row, t,
                                       fallback, nG)
        live_mol &= ~fallback

        # rejection tallies for non-fallback molecules
        vec = ~fallback
        stats.input_reads += int(sizes[vec].sum())
        n_fr = int(n_frag[vec].sum())
        if n_fr:
            stats.reject("FragmentRead", n_fr)
        gate_dead = vec & ~gate_ok & (n_paired > 0)
        if gate_dead.any():
            stats.reject("InsufficientReads", int(n_paired[gate_dead].sum()))
        coll_dead = vec & gate_ok & coll
        if coll_dead.any():
            stats.reject("PotentialCollision", int(n_paired[coll_dead].sum()))

        # molecule -> seg map for live molecules
        seg_map = np.full((nG, 4), -1, dtype=np.int64)
        if nseg:
            lm = live_mol[seg_g]
            seg_map[seg_g[lm], seg_t[lm]] = np.nonzero(lm)[0]

        # reserve this span's ordinal range NOW (stream order), so deferred
        # stage-2 resolution cannot shift the classic fallback numbering —
        # the simplex engine's _group_ordinal discipline (fast.py:499)
        ord0 = caller._ordinal
        caller._ordinal = ord0 + nG

        seg_len = np.zeros(nseg, dtype=np.int64)
        if nseg:
            fl = final_len[vrows]
            np.maximum.at(seg_len, seg_of_row, fl)

        # SS consensus for every seg: one kernel dispatch for multi-read
        # segs, one vectorized host pass for single-read segs
        L_max = stride
        ss_res = self._ss_consensus(codes, quals, vrows, c1, vstarts, nseg,
                                    L_max, defer=self.defer_device)
        if len(ss_res) == 2 and ss_res[0] == "defer":
            finish_ss = ss_res[1]

            def _finish():
                tb, tq, d16, e16, codes2d, ctx = finish_ss()
                return b"".join(self._stage2(
                    batch, span, gb, sizes, n_paired, fallback, sb,
                    live_mol, seg_map, seg_len, tb, tq, d16, e16,
                    codes2d, vrows, vstarts, L_max, ord0, ctx))

            return [_DuplexPending(_finish)]
        tb, tq, d16, e16, codes2d, ctx = ss_res
        return self._stage2(batch, span, gb, sizes, n_paired, fallback, sb,
                            live_mol, seg_map, seg_len, tb, tq, d16, e16,
                            codes2d, vrows, vstarts, L_max, ord0, ctx)

    def _need_filter_fallback(self, batch, span, vrows, g_of_row, t, fallback,
                              nG):
        """Mark molecules whose X or Y set would engage the alignment filter."""
        tt = t[vrows]
        setid = np.where((tt == AB_R1) | (tt == BA_R2), 0, 1)
        key = g_of_row[vrows] * 2 + setid
        order = np.argsort(key, kind="stable")
        srows = vrows[order]
        skey = key[order]
        if not len(skey):
            return
        sfirst = np.concatenate(([True], skey[1:] != skey[:-1]))
        sstarts = np.append(np.nonzero(sfirst)[0], len(skey))
        set_g = skey[sfirst] >> 1
        co = batch.cigar_off
        cl = (4 * batch.n_cigar).astype(np.int32)
        firsts = srows[sstarts[:-1]]
        counts = np.diff(sstarts)
        rep_first = np.repeat(firsts, counts)
        eq = nb.ranges_equal(batch.buf, co[span[srows]], cl[span[srows]],
                             co[span[rep_first]], cl[span[rep_first]])
        uniform = np.minimum.reduceat(eq, sstarts[:-1]).astype(bool)
        rev8 = ((batch.flag[span[srows]] & FLAG_REVERSE) != 0).astype(np.uint8)
        mn = np.minimum.reduceat(rev8, sstarts[:-1])
        mx = np.maximum.reduceat(rev8, sstarts[:-1])
        mixed = (mn == 0) & (mx == 1) & (counts >= 2)
        need = ~uniform
        if need.any():
            # all-single-op-M sets (ragged read lengths) are mutually
            # prefix-compatible after simplify: the alignment filter
            # provably keeps every read, so non-uniform bytes alone do not
            # require the fallback (fast.py _prepare_groups_vec twin)
            row_sm = (batch.n_cigar[span[srows]] == 1) \
                & ((batch.buf[co[span[srows]]] & 0xF) == 0)
            set_sm = np.minimum.reduceat(
                row_sm.astype(np.uint8), sstarts[:-1]).astype(bool)
            need &= ~set_sm
        for s in np.nonzero(uniform & mixed)[0]:
            rec_i = int(span[firsts[s]])
            if batch.n_cigar[rec_i] == 1:
                continue  # single-op simplified CIGARs are palindromic
            from ..core import cigar as cigar_utils
            from .fast import FastSimplexCaller

            cig = FastSimplexCaller._decode_cigar(batch, rec_i)
            simplified = cigar_utils.simplify(cig)
            if simplified != list(reversed(simplified)):
                need[s] = True
        fallback[set_g[need]] = True

    def _ss_consensus(self, codes, quals, vrows, c1, vstarts, nseg, L_max,
                      defer=False):
        """All segs' single-strand consensus: thresholded bases/quals and
        i16-clamped depth/error arrays, (nseg, L_max) each, plus the fused
        strand-combine context (None unless the full-column device route
        kept stage-1 outputs resident).

        defer=True + a device route: returns ("defer", finish) right after
        the dispatch; finish() -> the 6-tuple. Every other path stays
        synchronous (host compute has nothing to overlap; the sharded path
        fetches per shard)."""
        opts = self.ss.options
        tb = np.zeros((nseg, L_max), dtype=np.uint8)
        tq = np.zeros((nseg, L_max), dtype=np.uint8)
        d16 = np.zeros((nseg, L_max), dtype=np.int32)
        e16 = np.zeros((nseg, L_max), dtype=np.int32)
        if not nseg:
            return tb, tq, d16, e16, np.zeros((0, L_max), dtype=np.uint8), \
                None
        codes2d = np.ascontiguousarray(codes[vrows])
        quals2d = np.ascontiguousarray(quals[vrows])

        single = c1 == 1
        if single.any():
            rows = vrows[vstarts[:-1][single]]
            b, q, d, e = oracle.single_read_consensus(
                codes[rows], quals[rows], self.ss.tables,
                opts.min_consensus_base_quality)
            tb[single] = b
            tq[single] = q
            d16[single] = np.minimum(d, I16_MAX).astype(np.int32)
            # errors are zero for single-read consensus
        multi = np.nonzero(~single)[0]
        if not len(multi):
            return tb, tq, d16, e16, codes2d, None
        rows_m = np.concatenate(
            [np.arange(vstarts[s], vstarts[s + 1]) for s in multi])
        cm = np.ascontiguousarray(codes2d[rows_m])
        qm = np.ascontiguousarray(quals2d[rows_m])
        counts_m = c1[multi]
        starts_m = np.concatenate(([0], np.cumsum(counts_m)))

        def finish_with(w, q_, d, e, ctx):
            b_m, q_m = oracle.apply_consensus_thresholds(
                w, q_, d, opts.min_reads, opts.min_consensus_base_quality)
            tb[multi] = b_m
            tq[multi] = q_m
            d16[multi] = np.minimum(d, I16_MAX).astype(np.int32)
            e16[multi] = np.minimum(e, I16_MAX).astype(np.int32)
            return tb, tq, d16, e16, codes2d, ctx

        route = "host"
        if not self.kernel.host_mode():
            # adaptive offload: same pricing as the simplex engine (the
            # mesh size selects its own cost-model EWMA set)
            from ..ops.router import ROUTER

            route = ROUTER.decide_batch(
                self.kernel, cm.shape[0], len(multi), L_max,
                devices=self.mesh.size if self.mesh is not None else 1)
        if route == "host":
            # no device, or the cost model priced this batch host-side:
            # the native f64 engine absorbs it concurrently
            from ..ops.kernel import HOST_DISPATCH

            w, q_, d, e = self.kernel.resolve_segments(HOST_DISPATCH, cm,
                                                       qm, starts_m)
            return finish_with(w, q_, d, e, None)
        from ..ops.kernel import device_path

        if device_path() == "columns":
            # round-5 comparison route: classify + compact hard-column
            # export (FGUMI_TPU_DEVICE_PATH=columns)
            pending = self.kernel.dispatch_hard_columns(cm, qm, starts_m)

            def resolve_cols():
                w, q_, d, e = self.kernel.resolve_hard_columns(pending)
                return finish_with(w, q_, d, e, None)

            return ("defer", resolve_cols) if defer else resolve_cols()
        # full-column wire route (round-6 default): the whole multi-seg
        # pileup crosses the link once; with the resident variant the
        # thresholded outputs stay on device for the fused strand combine.
        # A > 1-device mesh runs the same kernels shard_map-wrapped
        # (families over dp, read rows over sp with one psum); the
        # resident arrays then live sharded along dp and the combine's
        # indices are mapped through the shard-order gather below.
        import os
        import time as _time

        from ..ops.kernel import pad_segments, pad_segments_mesh
        from ..ops.router import ROUTER

        comb_env = os.environ.get("FGUMI_TPU_DUPLEX_COMBINE",
                                  "auto").strip().lower()
        full_ok = bool(counts_m.max() < 65536)
        want_res = full_ok and comb_env != "host"
        t_pack0 = _time.monotonic()
        pred = ROUTER.last_prediction()
        res_thresholds = (opts.min_reads,
                          opts.min_consensus_base_quality) \
            if want_res else None
        mesh = self.mesh
        if mesh is not None:
            cg, qg, seg_g, _st, F_loc, gather = pad_segments_mesh(
                cm, qm, counts_m, mesh)
            ticket = self.kernel.device_call_segments_wire(
                cg, qg, seg_g, F_loc, len(multi), pack_t0=t_pack0,
                full=full_ok, resident_thresholds=res_thresholds,
                pred_s=pred[0] if pred else None, mesh=mesh,
                mesh_gather=gather)
        else:
            cd, qd, seg_ids, _sp, F_pad = pad_segments(cm, qm, counts_m)
            ticket = self.kernel.device_call_segments_wire(
                cd, qd, seg_ids, F_pad, len(multi), pack_t0=t_pack0,
                full=full_ok, resident_thresholds=res_thresholds,
                pred_s=pred[0] if pred else None)

        def resolve_wire():
            w, q_, d, e, extras = self.kernel.resolve_segments_wire(
                ticket, cm, qm, starts_m, want_extras=True)
            ctx = None
            if extras["resident"] is not None:
                seg_to_multi = np.full(nseg, -1, dtype=np.int64)
                seg_to_multi[multi] = np.arange(len(multi))
                ctx = {"resident": extras["resident"],
                       "suspect": extras["suspect"],
                       "seg_to_multi": seg_to_multi,
                       "override": comb_env,
                       # mesh dispatches: multi index -> row of the
                       # shard-ordered resident arrays
                       "gather": extras.get("gather")}
            return finish_with(w, q_, d, e, ctx)

        return ("defer", resolve_wire) if defer else resolve_wire()

    # ---------------------------------------------------------------- stage 2

    def _stage2(self, batch, span, gb, sizes, n_paired, fallback, sb,
                live_mol, seg_map, seg_len, tb, tq, d16, e16, codes2d,
                vrows, vstarts, L_max, ord0, combine_ctx=None):
        """Strand combination + serialization, molecule order preserved.

        ord0: the first ordinal of this span's pre-reserved range (set in
        _process_molecules before any deferral) — the global counter may
        already be past ord0 + nG when resolution is deferred, so it is
        save/restored around the classic fallback calls, never rewound."""
        caller = self.caller
        stats = caller.stats
        nG = len(sizes)

        p = seg_map >= 0
        full = p.all(axis=1) & live_mol
        ab_only = p[:, AB_R1] & p[:, AB_R2] & ~p[:, BA_R1] & ~p[:, BA_R2] \
            & live_mol & (caller.min_yx == 0)
        ba_only = ~p[:, AB_R1] & ~p[:, AB_R2] & p[:, BA_R1] & p[:, BA_R2] \
            & live_mol & (caller.min_yx == 0)

        # per-seg aliveness: any positive depth within a length limit.
        # One vector pass finds each seg's first positive-depth column;
        # the per-output-read check (lengths differ per pairing) is then a
        # scalar compare instead of a numpy any() per molecule
        pos_depth = d16 > 0
        has_depth = pos_depth.any(axis=1)
        first_nz = np.where(has_depth, np.argmax(pos_depth, axis=1), 1 << 30)

        def seg_alive(s, limit):
            return first_nz[s] < limit

        # build output reads in molecule order: 2 per emitted molecule
        out_specs = []   # (mol, flags, aseg, bseg, kind) kind: 2=combined,
        #                   1=a-passthrough, 0=b-passthrough(is_ba_only)
        emitted = np.zeros(nG, dtype=bool)
        col = np.arange(L_max)

        def classify(mol, a_s, b_s):
            """One output read's effective sides; None = dead molecule."""
            La, Lb = int(seg_len[a_s]) if a_s >= 0 else 0, \
                int(seg_len[b_s]) if b_s >= 0 else 0
            if a_s >= 0 and b_s >= 0:
                length = min(La, Lb)
                aa = seg_alive(a_s, length)
                ba = seg_alive(b_s, length)
                if aa and ba:
                    return (2, a_s, b_s, length)
                if aa:
                    return (1, a_s, -1, La)
                if ba:
                    return (0, b_s, -1, Lb)
                return None
            if a_s >= 0:
                return (1, a_s, -1, La) if seg_alive(a_s, La) else None
            if b_s >= 0:
                return (0, b_s, -1, Lb) if seg_alive(b_s, Lb) else None
            return None

        for g in np.nonzero(full | ab_only | ba_only)[0]:
            # rx1/rx2: the AB and BA segs contributing RX values per output
            # read — the reference folds in raws of BOTH segs even when one
            # strand's consensus is depth-dead (duplex.py:421-434 iterates
            # raws_a + raws_b of the branch taken)
            if full[g]:
                spec1 = classify(g, seg_map[g, AB_R1], seg_map[g, BA_R2])
                spec2 = classify(g, seg_map[g, AB_R2], seg_map[g, BA_R1])
                rx1 = (seg_map[g, AB_R1], seg_map[g, BA_R2])
                rx2 = (seg_map[g, AB_R2], seg_map[g, BA_R1])
                if spec1 is None or spec2 is None:
                    continue
                # _has_min_reads on both output reads (duplex.py:304-308)
                okmin = True
                for spec in (spec1, spec2):
                    kind, s1, s2, length = spec
                    na = int(d16[s1, :length].max()) if length else 0
                    nb_ = int(d16[s2, :length].max()) if kind == 2 and length \
                        else 0
                    xy, yx = max(na, nb_), min(na, nb_)
                    if not (caller.min_total <= xy + yx
                            and caller.min_xy <= xy and caller.min_yx <= yx):
                        okmin = False
                if not okmin:
                    continue
            elif ab_only[g]:
                spec1 = classify(g, seg_map[g, AB_R1], -1)
                spec2 = classify(g, seg_map[g, AB_R2], -1)
                rx1 = (seg_map[g, AB_R1], -1)
                rx2 = (seg_map[g, AB_R2], -1)
                if spec1 is None or spec2 is None:
                    continue
            else:
                spec1 = classify(g, -1, seg_map[g, BA_R2])
                spec2 = classify(g, -1, seg_map[g, BA_R1])
                rx1 = (-1, seg_map[g, BA_R2])
                rx2 = (-1, seg_map[g, BA_R1])
                if spec1 is None or spec2 is None:
                    continue
            emitted[g] = True
            out_specs.append((g, _TYPE_FLAGS[R1]) + spec1 + rx1)
            out_specs.append((g, _TYPE_FLAGS[R2]) + spec2 + rx2)

        # InsufficientReads for live-but-unemitted molecules (the fallthrough
        # reject in _combine_molecule, duplex.py:361-363)
        dead = live_mol & ~emitted
        if dead.any():
            stats.reject("InsufficientReads", int(n_paired[dead].sum()))

        K = len(out_specs)
        chunks = []
        fast_blob = b""
        rec_end = np.zeros(0, dtype=np.int64)
        if K:
            fast_blob, rec_end = self._serialize_outputs(
                batch, span, gb, out_specs, seg_map, seg_len, tb, tq, d16,
                e16, codes2d, vrows, vstarts, L_max, col, combine_ctx)
            stats.consensus_reads += K
        elif combine_ctx is not None:
            # nothing to combine this span: drop the resident accounting
            combine_ctx["resident"].release()

        # assemble in molecule order, interleaving fallback molecules
        fb_set = set(np.nonzero(fallback)[0].tolist())
        if not fb_set:
            return [fast_blob] if fast_blob else []
        out_i = 0
        pending_fast_start = 0
        saved_ordinal = caller._ordinal
        for g in sorted(fb_set):
            # flush the fast run before this molecule
            while out_i < len(out_specs) and out_specs[out_i][0] < g:
                out_i += 2
            run_end = int(rec_end[out_i - 1]) if out_i else 0
            if run_end > pending_fast_start:
                chunks.append(fast_blob[pending_fast_start:run_end])
                pending_fast_start = run_end
            rows = span[gb[g]:gb[g + 1]]
            sb_g = sb[gb[g]:gb[g + 1]]
            a = batch.raw_records(rows[~sb_g])
            b = batch.raw_records(rows[sb_g])
            caller._ordinal = ord0 + g
            chunks.extend(self._call_slow_molecule(
                self._base_mi(batch, int(rows[0])), a, b, corrected=True))
        caller._ordinal = saved_ordinal
        if len(fast_blob) > pending_fast_start:
            chunks.append(fast_blob[pending_fast_start:])
        return chunks

    def _serialize_outputs(self, batch, span, gb, out_specs, seg_map, seg_len,
                           tb, tq, d16, e16, codes2d, vrows, vstarts, L_max,
                           col, combine_ctx=None):
        """Combine + native-serialize the K fast output reads (order kept).

        The strand combine runs either as numpy (the semantic reference) or
        as the fused device stage over the stage-1 resident SS arrays
        (``combine_ctx``; ops/kernel._duplex_combine_jit) — integer-exact
        twins, chosen per batch by the adaptive cost model. Output rows
        whose inputs carry an oracle patch (suspect positions) always take
        the host combine: the resident arrays are pre-patch."""
        caller = self.caller
        K = len(out_specs)
        mols = np.array([s[0] for s in out_specs], dtype=np.int64)
        flags = np.array([s[1] for s in out_specs], dtype=np.int32)
        kinds = np.array([s[2] for s in out_specs], dtype=np.int8)
        aseg = np.array([s[3] for s in out_specs], dtype=np.int64)
        bseg = np.array([s[4] for s in out_specs], dtype=np.int64)
        lens = np.array([s[5] for s in out_specs], dtype=np.int32)

        out_b = np.zeros((K, L_max), dtype=np.uint8)
        out_q = np.zeros((K, L_max), dtype=np.uint8)
        out_e = np.zeros((K, L_max), dtype=np.int32)

        comb = np.nonzero(kinds == 2)[0]

        def combine_host(sel):
            """Numpy strand combine for output rows `sel` (the semantic
            reference the device stage must match bit-for-bit)."""
            ca, cb = aseg[sel], bseg[sel]
            a_b = tb[ca].astype(np.int32)
            b_b = tb[cb].astype(np.int32)
            a_q = tq[ca].astype(np.int32)
            b_q = tq[cb].astype(np.int32)
            agree = a_b == b_b
            a_wins = (~agree) & (a_q > b_q)
            b_wins = (~agree) & (b_q > a_q)
            tie = (~agree) & (a_q == b_q)
            raw_base = np.where(agree | a_wins, a_b, b_b)
            raw_qual = np.where(
                agree, np.clip(a_q + b_q, MIN_PHRED, MAX_PHRED),
                np.where(a_wins, np.clip(a_q - b_q, MIN_PHRED, MAX_PHRED),
                         np.where(b_wins, np.clip(b_q - a_q, MIN_PHRED,
                                                  MAX_PHRED), MIN_PHRED)))
            either_n = (a_b == N_CODE) | (b_b == N_CODE)
            mask = either_n | (raw_qual == MIN_PHRED) | tie
            in_len = col[None, :] < lens[sel, None]
            out_b[sel] = np.where(in_len & ~mask, raw_base, N_CODE)
            out_q[sel] = np.where(in_len & ~mask, raw_qual, MIN_PHRED)
            out_b[sel] = np.where(in_len, out_b[sel], 0)
            out_q[sel] = np.where(in_len, out_q[sel], 0)
            # exact per-base errors vs the pre-mask raw duplex base over both
            # segs' packed source rows (duplex.py:118-126), with positions at
            # or beyond the combined length excluded per source read
            rb8 = np.ascontiguousarray(raw_base.astype(np.uint8))
            errs = np.zeros((len(sel), L_max), dtype=np.int32)
            for side in (ca, cb):
                # one native pass per side over each output's seg row range
                _, e_side = nb.segment_depth_errors_ranges(
                    codes2d, rb8, vstarts[:-1][side], vstarts[1:][side])
                errs += e_side
            errs[rb8 == N_CODE] = 0
            errs[~in_len] = 0
            out_e[sel] = np.minimum(errs, I16_MAX)

        done_rows = np.empty(0, dtype=np.int64)
        if len(comb) and combine_ctx is not None:
            s2m = combine_ctx["seg_to_multi"]
            ma = s2m[aseg[comb]]
            mb = s2m[bseg[comb]]
            eligible = (ma >= 0) & (mb >= 0)  # single-read segs: host-only
            sus = combine_ctx["suspect"]
            if sus is not None and eligible.any():
                # any oracle-patched position on either strand sends the
                # whole output row to the host combine (resident arrays
                # are pre-patch; conservative over the full row width)
                sus_row = sus.any(axis=1)
                eligible &= ~(sus_row[np.maximum(ma, 0)]
                              | sus_row[np.maximum(mb, 0)])
            cand = comb[eligible]
            if len(cand):
                from ..ops.kernel import duplex_combine_device
                from ..ops.router import DUPLEX_COMBINE, run_adaptive_stage

                # mesh dispatches keep the resident arrays shard-ordered
                # on device: remap multi indices through the gather instead
                # of paying a device-side re-shuffle (single-device: rows
                # ARE multi order, gather is None)
                gather = combine_ctx.get("gather")
                a_rows = s2m[aseg[cand]]
                b_rows = s2m[bseg[cand]]
                if gather is not None:
                    a_rows = gather[a_rows]
                    b_rows = gather[b_rows]

                def _device_combine():
                    ob, oq, oe = duplex_combine_device(
                        combine_ctx["resident"], a_rows, b_rows,
                        lens[cand])
                    out_b[cand] = ob
                    out_q[cand] = oq
                    out_e[cand] = oe

                run_adaptive_stage(DUPLEX_COMBINE, len(cand) * L_max,
                                   combine_ctx.get("override", "auto"),
                                   _device_combine,
                                   lambda: combine_host(cand))
                done_rows = cand
        rest = np.setdiff1d(comb, done_rows)
        if len(rest):
            # suspect-touched / single-seg / no-resident rows: always the
            # host combine (not a chooser sample — the cand subset is the
            # measured apples-to-apples comparison)
            combine_host(rest)
        if combine_ctx is not None:
            # the fused combine is done with the stage-1 resident arrays:
            # release their device-byte accounting (ISSUE 11 satellite)
            combine_ctx["resident"].release()

        passthrough = np.nonzero(kinds != 2)[0]
        for k in passthrough:
            s = aseg[k]
            L = lens[k]
            out_b[k, :L] = tb[s, :L]
            out_q[k, :L] = tq[s, :L]
            out_e[k, :L] = e16[s, :L]

        # serializer strand inputs: 'a' side = dup.ab_consensus (the alive /
        # AB side, truncated to the combined length), 'b' side =
        # ba_consensus (combined case only)
        a_rows = aseg
        a_len = lens.astype(np.int32)
        b_present = (kinds == 2).astype(np.uint8)
        b_rows = np.where(kinds == 2, bseg, 0)
        b_len = np.where(kinds == 2, lens, 0).astype(np.int32)

        def row_addrs(arr, rows):
            return arr.ctypes.data + rows * arr.shape[1] * arr.itemsize

        # RX per output read (strand-reoriented consensus, duplex.py:421-434)
        rx_addr, rx_len, keep_alive = self._output_rx(
            batch, span, out_specs, seg_map, vrows, vstarts)

        mi_off, mi_len, _ = batch.tag_locs(self.tag)
        first_rows = span[gb[mols]]
        mi_addr = batch.buf.ctypes.data + mi_off[first_rows]
        mi_l = (mi_len[first_rows] - 2).astype(np.int32)  # base MI, no /A|/B

        blob, rec_end = nb.build_duplex_records(
            row_addrs(out_b, np.arange(K)), row_addrs(out_q, np.arange(K)),
            row_addrs(out_e, np.arange(K)), lens, flags,
            caller.prefix.encode(), mi_addr, mi_l,
            row_addrs(tb, a_rows), row_addrs(tq, a_rows),
            row_addrs(d16, a_rows), row_addrs(e16, a_rows), a_len,
            row_addrs(tb, b_rows), row_addrs(tq, b_rows),
            row_addrs(d16, b_rows), row_addrs(e16, b_rows), b_len, b_present,
            rx_addr, rx_len, caller.read_group_id.encode(),
            caller.produce_per_base_tags)
        del keep_alive
        return blob, rec_end

    def _output_rx(self, batch, span, out_specs, seg_map, vrows, vstarts):
        """RX tag per output read: a-side values verbatim, b-side values
        strand-flipped, then the UMI consensus (unanimous fast path)."""
        rx_vo, rx_vl, _ = batch.tag_locs_str(b"RX")
        buf = batch.buf
        K = len(out_specs)
        rx_off_in_blob = np.zeros(K, dtype=np.int64)
        rx_len = np.zeros(K, dtype=np.int32)
        blob = bytearray()  # one allocation for all values, not one per emit

        span_v = span[vrows]
        una_off, una_len = nb.rx_unanimous(buf, rx_vo[span_v], rx_vl[span_v],
                                           vstarts)
        present = (rx_vo[span_v] >= 0).astype(np.int64)
        cnt = np.add.reduceat(present, vstarts[:-1]) \
            if len(span_v) else np.zeros(0, dtype=np.int64)

        # native fast path: every output whose contributing segs are
        # unanimous/absent resolves in one C pass (single-read verbatim /
        # all-equal uppercased, b-side flip on bytes); only divergent or
        # disagreeing outputs fall through to the Python likelihood loop
        fb_set = None
        if K and nb.available():
            a_arr = np.fromiter((s[6] for s in out_specs), np.int64, K)
            b_arr = np.fromiter((s[7] for s in out_specs), np.int64, K)
            n_off, n_len, n_blob, fb = nb.duplex_rx_fast(
                buf, una_off, una_len, cnt, a_arr, b_arr)
            if len(fb) == 0:
                blob_arr = n_blob if len(n_blob) else \
                    np.zeros(1, dtype=np.uint8)
                rx_addr = np.where(n_len > 0,
                                   blob_arr.ctypes.data + n_off, 0)
                return rx_addr, n_len, [blob_arr]
            fb_set = set(int(x) for x in fb)

        def seg_values(s):
            """Ordered present RX strings of seg s."""
            rows = span_v[vstarts[s]:vstarts[s + 1]]
            vals = []
            for i in rows:
                if rx_vo[i] >= 0:
                    vals.append(buf[rx_vo[i]:rx_vo[i] + rx_vl[i]]
                                .tobytes().decode())
            return vals

        def emit(k, rx):
            rx_off_in_blob[k] = len(blob)
            blob.extend(rx.encode())
            rx_len[k] = len(rx)

        fams = []
        fam_ks = []
        for k, spec in enumerate(out_specs):
            if fb_set is not None and k not in fb_set:
                continue  # resolved by the native fast path
            # AB-seg values verbatim, BA-seg values flipped — BOTH segs of
            # the branch contribute, independent of consensus aliveness
            a_s, b_s = spec[6], spec[7]
            # fast path: when every contributing seg is unanimous, the
            # family holds at most two distinct values — if they agree, the
            # consensus is that value (simple_umi's all-equal rule: verbatim
            # for a single read, ACGTN-uppercased otherwise), with no
            # per-read list or likelihood call. This is ~every real duplex
            # molecule (a-strand RX == flip(b-strand RX)).
            svals = []
            simple = True
            for s, flip in ((a_s, False), (b_s, True)):
                if s < 0 or una_off[s] == -1:
                    continue
                if una_off[s] == -2:
                    simple = False
                    break
                v = buf[una_off[s]:una_off[s] + una_len[s]].tobytes().decode()
                if flip:
                    v = _flip_umi(v)
                svals.append((v, int(cnt[s])))
            if simple:
                if not svals:
                    continue
                total = sum(c for _, c in svals)
                if total == 1:
                    emit(k, svals[0][0])
                    continue
                if all(v == svals[0][0] for v, _ in svals):
                    emit(k, svals[0][0].translate(_ACGTN_UPPER))
                    continue
            vals = []
            for s, flip in ((a_s, False), (b_s, True)):
                if s < 0:
                    continue
                if una_off[s] == -2:  # divergent: materialize + flip each
                    vs = seg_values(s)
                    if flip:
                        vs = [_flip_umi(v) for v in vs]
                elif una_off[s] >= 0:  # unanimous: decode (and flip) ONCE
                    v = buf[una_off[s]:una_off[s] + una_len[s]] \
                        .tobytes().decode()
                    if flip:
                        v = _flip_umi(v)
                    vs = [v] * int(cnt[s])
                else:
                    continue
                vals.extend(vs)
            if not vals:
                continue
            fams.append(vals)
            fam_ks.append(k)
        for k, rx in zip(fam_ks, consensus_umis_batch(fams)):
            emit(k, rx)
        blob_arr = np.frombuffer(bytes(blob) or b"\x00", dtype=np.uint8)
        if fb_set is not None:
            # merge: python-resolved (fallback) outputs override the
            # native arena's entries; both arenas stay alive via the
            # returned keepalive list
            n_blob_arr = n_blob if len(n_blob) else np.zeros(1, np.uint8)
            py_mask = rx_len > 0
            rx_addr = np.where(
                py_mask, blob_arr.ctypes.data + rx_off_in_blob,
                np.where(n_len > 0, n_blob_arr.ctypes.data + n_off, 0))
            return (rx_addr, np.where(py_mask, rx_len, n_len),
                    [blob_arr, n_blob_arr])
        rx_addr = np.where(rx_len > 0,
                           blob_arr.ctypes.data + rx_off_in_blob, 0)
        return rx_addr, rx_len, [blob_arr]
