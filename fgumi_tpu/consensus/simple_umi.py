"""Simple (equal-quality) UMI-string consensus.

Mirrors /root/reference/crates/fgumi-consensus/src/simple_umi.rs: per-position
likelihood consensus with flat Q20 observations and Q90/Q90 error rates; non-DNA
characters (e.g. the '-' separator in duplex UMIs) must be uniform per column and are
preserved from the first sequence. Used for the consensus RX tag
(vanilla_caller.rs:1522-1536).
"""

import numpy as np

from ..constants import BASE_TO_CODE, CODE_TO_BASE
from ..ops import oracle
from ..ops.tables import quality_tables

_DNA = frozenset(b"ACGTNacgtn")
_Q_ERROR = 20


def consensus_umis(umis) -> str:
    """Majority/likelihood consensus over equal-length UMI strings (simple_umi.rs:236-245)."""
    if not umis:
        return ""
    if len(umis) == 1:
        return umis[0]
    seq_len = len(umis[0])
    if any(len(u) != seq_len for u in umis):
        raise ValueError(f"UMI sequences must all have the same length: {umis}")

    arr = np.array([np.frombuffer(u.encode(), dtype=np.uint8) for u in umis])  # (R, L)
    is_dna = np.isin(arr, np.frombuffer(bytes(_DNA), dtype=np.uint8))
    codes = np.where(is_dna, BASE_TO_CODE[arr], 4).astype(np.uint8)
    quals = np.full_like(codes, _Q_ERROR)

    tables = quality_tables(90, 90)
    winner, _q, _d, _e = oracle.call_family(codes, quals, tables)

    out = bytearray()
    first = arr[0]
    n_dna = is_dna.sum(axis=0)
    for i in range(seq_len):
        if n_dna[i] == 0:
            # all non-DNA: must be the same character, preserved from the first
            if not (arr[:, i] == first[i]).all():
                raise ValueError(
                    f"Sequences must have character {chr(first[i])!r} at position {i}")
            out.append(first[i])
        elif n_dna[i] == len(umis):
            out.append(CODE_TO_BASE[winner[i]])
        else:
            raise ValueError(
                f"Sequences contained a mix of DNA and non-DNA characters at offset {i}")
    return out.decode()
