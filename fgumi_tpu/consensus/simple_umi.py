"""Simple (equal-quality) UMI-string consensus.

Mirrors /root/reference/crates/fgumi-consensus/src/simple_umi.rs: per-position
likelihood consensus with flat Q20 observations and Q90/Q90 error rates; non-DNA
characters (e.g. the '-' separator in duplex UMIs) must be uniform per column and are
preserved from the first sequence. Used for the consensus RX tag
(vanilla_caller.rs:1522-1536).
"""

import numpy as np

from ..constants import BASE_TO_CODE, CODE_TO_BASE
from ..ops import oracle
from ..ops.tables import quality_tables

_DNA = frozenset(b"ACGTNacgtn")
_Q_ERROR = 20
_tables = None

# the all-equal rule "uppercase a char iff its uppercase is in ACGTN" is
# exactly an acgtn->ACGTN translation: only those five lowercase letters
# have an uppercase image in the set, everything else passes through
_ACGTN_UPPER = str.maketrans("acgtn", "ACGTN")


def consensus_umis_batch(families) -> list:
    """[consensus_umis(f) for f in families], with all non-trivial families
    resolved in ONE oracle pass.

    Exactness: every oracle op (Kahan accumulation, log-sum-exp, tie rule) is
    positionwise, and N-padded rows are skipped by the masked Kahan update,
    so concatenating families along the position axis (rows padded to the
    common R with N) yields bit-identical results to per-family calls —
    including the accumulation-order-pinned near-tie behavior.
    """
    results = [None] * len(families)
    work = []
    for i, umis in enumerate(families):
        if not umis:
            results[i] = ""
            continue
        first = umis[0]
        if len(umis) == 1:
            results[i] = first
            continue
        if all(u == first for u in umis):
            results[i] = first.translate(_ACGTN_UPPER)
            continue
        work.append(i)
    if not work:
        return results

    dna_set = np.frombuffer(bytes(_DNA), dtype=np.uint8)
    arrs, dnas, codes_list = [], [], []
    R_max = 0
    for i in work:
        umis = families[i]
        seq_len = len(umis[0])
        if any(len(u) != seq_len for u in umis):
            raise ValueError(
                f"UMI sequences must all have the same length: {umis}")
        arr = np.array([np.frombuffer(u.encode(), dtype=np.uint8)
                        for u in umis])
        is_dna = np.isin(arr, dna_set)
        codes = np.where(is_dna, BASE_TO_CODE[arr], 4).astype(np.uint8)
        arrs.append(arr)
        dnas.append(is_dna)
        codes_list.append(codes)
        R_max = max(R_max, arr.shape[0])

    cat = np.concatenate(
        [np.pad(c, ((0, R_max - c.shape[0]), (0, 0)), constant_values=4)
         for c in codes_list], axis=1)
    quals = np.full_like(cat, _Q_ERROR)
    global _tables
    if _tables is None:
        _tables = quality_tables(90, 90)
    winner_cat, _q, _d, _e = oracle.call_family(cat, quals, _tables)

    off = 0
    for i, arr, is_dna in zip(work, arrs, dnas):
        seq_len = arr.shape[1]
        winner = winner_cat[off:off + seq_len]
        off += seq_len
        results[i] = _assemble(arr, is_dna, winner, len(families[i]))
    return results


def _assemble(arr, is_dna, winner, n_umis) -> str:
    """Winner codes + non-DNA column rules -> consensus string."""
    seq_len = arr.shape[1]
    out = bytearray()
    first_arr = arr[0]
    n_dna = is_dna.sum(axis=0)
    for i in range(seq_len):
        if n_dna[i] == 0:
            if not (arr[:, i] == first_arr[i]).all():
                raise ValueError(
                    f"Sequences must have character {chr(first_arr[i])!r} "
                    f"at position {i}")
            out.append(first_arr[i])
        elif n_dna[i] == n_umis:
            out.append(CODE_TO_BASE[winner[i]])
        else:
            raise ValueError(
                f"Sequences contained a mix of DNA and non-DNA characters "
                f"at offset {i}")
    return out.decode()


def consensus_umis(umis) -> str:
    """Majority/likelihood consensus over equal-length UMI strings (simple_umi.rs:236-245).

    Unanimous inputs (the overwhelmingly common case — UMI errors are rare
    within a family) short-circuit: the flat-quality likelihood winner of R
    identical strings is trivially that string. Non-unanimous inputs run the
    f64 oracle with flat Q20 observations; near-exact likelihood ties there
    resolve by accumulation-order rounding, which is pinned implementation
    behavior a counting shortcut cannot reproduce, so the oracle stays the
    source of truth (tests/test_simple_umi.py).
    """
    return consensus_umis_batch([umis])[0]
