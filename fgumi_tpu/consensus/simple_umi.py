"""Simple (equal-quality) UMI-string consensus.

Mirrors /root/reference/crates/fgumi-consensus/src/simple_umi.rs: per-position
likelihood consensus with flat Q20 observations and Q90/Q90 error rates; non-DNA
characters (e.g. the '-' separator in duplex UMIs) must be uniform per column and are
preserved from the first sequence. Used for the consensus RX tag
(vanilla_caller.rs:1522-1536).
"""

import numpy as np

from ..constants import BASE_TO_CODE, CODE_TO_BASE
from ..ops import oracle
from ..ops.tables import quality_tables

_DNA = frozenset(b"ACGTNacgtn")
_Q_ERROR = 20
_tables = None


def consensus_umis(umis) -> str:
    """Majority/likelihood consensus over equal-length UMI strings (simple_umi.rs:236-245).

    Unanimous inputs (the overwhelmingly common case — UMI errors are rare
    within a family) short-circuit: the flat-quality likelihood winner of R
    identical strings is trivially that string. Non-unanimous inputs run the
    f64 oracle with flat Q20 observations; near-exact likelihood ties there
    resolve by accumulation-order rounding, which is pinned implementation
    behavior a counting shortcut cannot reproduce, so the oracle stays the
    source of truth (tests/test_simple_umi.py).
    """
    if not umis:
        return ""
    first = umis[0]
    if len(umis) == 1:
        return first  # single-sequence passthrough (verbatim, original casing)
    if all(u == first for u in umis):
        # match the oracle path's output casing exactly: DNA characters come
        # back uppercased (CODE_TO_BASE), non-DNA characters pass through
        return "".join(c.upper() if c.upper() in "ACGTN" else c
                       for c in first)
    seq_len = len(first)
    if any(len(u) != seq_len for u in umis):
        raise ValueError(f"UMI sequences must all have the same length: {umis}")

    arr = np.array([np.frombuffer(u.encode(), dtype=np.uint8) for u in umis])  # (R, L)
    is_dna = np.isin(arr, np.frombuffer(bytes(_DNA), dtype=np.uint8))
    codes = np.where(is_dna, BASE_TO_CODE[arr], 4).astype(np.uint8)
    quals = np.full_like(codes, _Q_ERROR)

    global _tables
    if _tables is None:
        _tables = quality_tables(90, 90)
    winner, _q, _d, _e = oracle.call_family(codes, quals, _tables)

    out = bytearray()
    first_arr = arr[0]
    n_dna = is_dna.sum(axis=0)
    for i in range(seq_len):
        if n_dna[i] == 0:
            # all non-DNA: must be the same character, preserved from the first
            if not (arr[:, i] == first_arr[i]).all():
                raise ValueError(
                    f"Sequences must have character {chr(first_arr[i])!r} at position {i}")
            out.append(first_arr[i])
        elif n_dna[i] == len(umis):
            out.append(CODE_TO_BASE[winner[i]])
        else:
            raise ValueError(
                f"Sequences contained a mix of DNA and non-DNA characters at offset {i}")
    return out.decode()
