"""Vectorized CODEC host prep over RecordBatch inputs.

Replaces CodecConsensusCaller.prepare()'s record-level work (phases 1-5 of
codec_caller.rs:589-836) with batch arrays for the dominant CODEC shape —
every paired primary a single-op M CIGAR — where clip amounts, adjusted
positions, overlap geometry, and the phase checks are closed-form
arithmetic and the SourceRead conversion is one native pack. Molecules with
any other CIGAR shape run the classic prepare() unchanged, in stream order
(sharing the caller's stats and downsample RNG stream).

Stage 2 (the SS device pass, geometry finish, combine/masks, record build)
IS the classic caller's `_run_jobs` + `_finish`, so outputs are identical
by construction; tests/test_fast_codec.py asserts byte parity end to end.
"""

import struct

import numpy as np

from ..constants import (CODE_TO_BASE, MIN_PHRED, N_CODE, NO_CALL_BASE,
                         NO_CALL_BASE_LOWER)
from ..io.bam import (FLAG_FIRST, FLAG_MATE_REVERSE, FLAG_MATE_UNMAPPED,
                      FLAG_PAIRED, FLAG_REVERSE, FLAG_SECONDARY,
                      FLAG_SUPPLEMENTARY, FLAG_UNMAPPED)
from ..native import batch as nb
from .codec import _ASCII_COMPLEMENT, _SS, combine_arrays


class FastCodecCaller:
    """Batch CODEC engine wrapping a CodecConsensusCaller."""

    def __init__(self, caller, tag: bytes = b"MI", mesh=None):
        """`mesh`: optional jax Mesh with (dp, sp) axes — the SS device
        pass routes through the shard_map-wrapped wire kernels and the
        concordance combine through the sharded elementwise variant. None
        or a 1-device mesh = the legacy single-device path, bit for bit."""
        self.caller = caller
        # device/host routing is per batch via the adaptive cost model
        # (ops/router.py; FGUMI_TPU_ROUTE / FGUMI_TPU_MAX_INFLIGHT handled
        # inside ROUTER.decide)
        self.tag = tag
        self.mesh = mesh if mesh is not None and mesh.size > 1 else None
        self._carry = None  # (mi string, [RawRecord])

    # ----------------------------------------------------------------- driver

    def process_batch(self, batch, final: bool = False):
        """Consume one RecordBatch -> serialized consensus blobs.

        Each returned chunk carries its records' block_size prefixes
        (BamWriter.write_serialized framing)."""
        n = batch.n
        if n == 0:
            return self.flush() if final else []
        buf = batch.buf
        # Z/H-typed presence gate matches the classic get_str-based grouping
        mi_off, mi_len, _ = batch.tag_locs_str(self.tag)
        if (mi_off < 0).any():
            bad = int(np.nonzero(mi_off < 0)[0][0])
            raise ValueError(
                f"record {batch.name(bad)!r} missing {self.tag.decode()} tag")
        starts = nb.group_starts(buf, np.ascontiguousarray(mi_off),
                                 mi_len)
        bounds = np.append(starts, n)
        n_total = len(bounds) - 1

        first_mi = batch.tag_bytes(self.tag, int(bounds[0])).decode()
        merge_carry = self._carry is not None and self._carry[0] == first_mi
        if merge_carry:
            self._carry[1].extend(
                batch.raw_records(np.arange(bounds[0], bounds[1])))

        g0 = 1 if merge_carry else 0
        g1 = n_total if final else max(n_total - 1, g0)
        deferred = None
        if not final and n_total - 1 >= g0:
            lo, hi = bounds[n_total - 1], bounds[n_total]
            deferred = (batch.tag_bytes(self.tag, int(lo)).decode(),
                        batch.raw_records(np.arange(lo, hi)))

        molecules = []
        if self._carry is not None:
            if (not merge_carry) or final or n_total >= 2:
                mi, recs = self._carry
                self._carry = None
                mol = self.caller.prepare(recs, umi=mi)
                if mol is not None:
                    molecules.append(mol)

        codes_pk = quals_pk = None
        if g1 > g0:
            span_mols, codes_pk, quals_pk = self._prepare_span(batch, bounds,
                                                               g0, g1)
            molecules.extend(span_mols)

        if deferred is not None:
            self._carry = deferred

        out = self._run(molecules, codes_pk, quals_pk)
        if final:
            out.extend(self.flush())
        return out

    def flush(self):
        if self._carry is None:
            return []
        mi, recs = self._carry
        self._carry = None
        mol = self.caller.prepare(recs, umi=mi)
        return self._run([mol] if mol is not None else [])

    def _run(self, molecules, codes_pk=None, quals_pk=None):
        """One SS device pass + batched finish.

        Vec-prepared molecules (strand rows resident in the pack arrays)
        land in the dense layout via ONE gather from codes_pk/quals_pk —
        the same pad_segments/device_call_segments/thresholds sequence as
        VanillaConsensusCaller._run_jobs, minus the per-read row repack.
        Classic-prepared molecules (carry/fallback ConsensusJobs) repack
        their few rows into the same layout, so every batch costs exactly
        one device execution.
        """
        from ..ops import oracle
        from .vanilla import I16_MAX, VanillaConsensusRead

        caller = self.caller
        ss = caller.ss
        if not molecules:
            return []
        strand_res = {}  # (mol_idx, strand) -> (bases, quals, depths, errs)

        vec_multi = []       # (mol_idx, strand, base_row, count, cl)
        classic_multi = []   # (mol_idx, strand, job)
        for i, m in enumerate(molecules):
            if "job_r1" in m:
                # carry/fallback molecules: the same dispatch, rows repacked
                # below (a separate _run_jobs call would cost a second
                # device execution on essentially every streamed batch)
                for s, job in enumerate((m["job_r1"], m["job_r2"])):
                    cl = job.consensus_len
                    if len(job.codes) == 1:
                        strand_res[(i, s)] = oracle.single_read_consensus(
                            job.codes[0][:cl], job.quals[0][:cl], ss.tables,
                            ss.options.min_consensus_base_quality)
                    else:
                        classic_multi.append((i, s, job))
                continue
            base = m["pk0"]
            for s, (b0, cnt, flens) in enumerate(
                    ((base, m["n_r1"], m["r1_flens"]),
                     (base + m["n_r1"], m["n_r2"], m["r2_flens"]))):
                cl = int(flens.max())
                if cnt == 1:
                    strand_res[(i, s)] = oracle.single_read_consensus(
                        codes_pk[b0, :cl], quals_pk[b0, :cl], ss.tables,
                        ss.options.min_consensus_base_quality)
                else:
                    vec_multi.append((i, s, b0, cnt, cl))

        if vec_multi or classic_multi:
            cls = [(i, s, job.consensus_len, job)
                   for i, s, job in classic_multi]
            all_cl = [v[4] for v in vec_multi] + [c[2] for c in cls]
            L_max = max(-(-max(all_cl) // 16) * 16, 16)
            counts = np.array([v[3] for v in vec_multi]
                              + [len(c[3].codes) for c in cls],
                              dtype=np.int64)
            n_vec_rows = int(sum(v[3] for v in vec_multi))
            N = int(counts.sum())
            codes2d = np.full((N, L_max), N_CODE, dtype=np.uint8)
            quals2d = np.zeros((N, L_max), dtype=np.uint8)
            if vec_multi:
                rows_idx = np.concatenate(
                    [np.arange(b0, b0 + cnt)
                     for _, _, b0, cnt, _ in vec_multi])
                # pack rows are N/Q0-padded past each read's final length,
                # so a single fancy-index gather IS the dense job layout.
                # A carry molecule's longer reads can push L_max past the
                # span's pack stride; vec flens never exceed the stride, so
                # clamping the gather width keeps the tail at N/Q0.
                wv = min(L_max, codes_pk.shape[1])
                codes2d[:n_vec_rows, :wv] = codes_pk[rows_idx, :wv]
                quals2d[:n_vec_rows, :wv] = quals_pk[rows_idx, :wv]
            row = n_vec_rows
            for _, _, _, job in cls:
                for c, q in zip(job.codes, job.quals):
                    k = min(len(c), L_max)
                    codes2d[row, :k] = c[:k]
                    quals2d[row, :k] = q[:k]
                    row += 1
            # adaptive offload: host f64 engine / hard-column export /
            # full-column wire, decided per batch (ops/kernel helper)
            from ..ops.kernel import route_and_call_segments

            starts = np.concatenate(([0], np.cumsum(counts)))
            w, q_, d, e = route_and_call_segments(ss.kernel, codes2d,
                                                  quals2d, counts, starts,
                                                  mesh=self.mesh)
            slots = [(v[0], v[1], v[4]) for v in vec_multi] \
                + [(c[0], c[1], c[2]) for c in cls]
            # thresholds are elementwise: one vectorized pass over the whole
            # (F, L) batch, then per-slot length slicing (positions past a
            # slot's consensus length are computed and discarded)
            b_all, q_all = oracle.apply_consensus_thresholds(
                w, q_, d, ss.options.min_reads,
                ss.options.min_consensus_base_quality)
            for fi, (i, s, cl) in enumerate(slots):
                strand_res[(i, s)] = ("slot", fi, cl)
            slot_mats = (b_all, q_all, d, e)
        else:
            slot_mats = None
        return self._finish_batch(molecules, strand_res, slot_mats)

    @staticmethod
    def _strand_len(entry) -> int:
        # slot refs are ("slot", row, len) 3-tuples; materialized strands
        # are (bases, quals, depths, errors) 4-tuples of arrays
        return entry[2] if len(entry) == 3 else len(entry[0])

    def _finish_batch(self, molecules, strand_res, slot_mats):
        """Batched `_finish` (codec.py:527-568): strand geometry lands in
        concatenated position arrays, the duplex combine + quality-mask math
        of codec.py:360-456 runs once over all molecules (each molecule's
        slice is element-identical to the per-molecule version), and records
        serialize per molecule. Stats totals match the sequential path.

        Strand results arrive either as ("slot", row, len) references into
        the batch (F, L) result matrices (the common case — the whole
        orient/pad placement runs as ONE gather+scatter instead of 2 numpy
        calls per molecule) or as materialized arrays (single-read and
        classic-carry strands), placed scalarly."""
        from .vanilla import I16_MAX

        caller = self.caller
        st, opts = caller.stats, caller.options
        keep = []
        for i, mol in enumerate(molecules):
            en1, en2 = strand_res[(i, 0)], strand_res[(i, 1)]
            L = mol["consensus_length"]
            if L < self._strand_len(en1) or L < self._strand_len(en2):
                st.reject("ClipOverlapFailed", mol["n_r1"] + mol["n_r2"])
                continue
            keep.append((mol, en1, en2))
        if not keep:
            return []
        J = len(keep)
        # ONE pass over the kept molecules collects every per-molecule
        # scalar the batched placement/serialization needs (this loop ran
        # five times before: lengths, two placement passes, rc flags,
        # rejects)
        Ls = np.empty(J, dtype=np.int64)
        r1n = np.empty(J, dtype=bool)
        r2n = np.empty(J, dtype=bool)
        slot_j = ([], [])
        slot_row = ([], [])
        slot_k = ([], [])
        arr_items = []  # (side, j, en) — materialized strands, placed scalarly
        for j, (mol, en1, en2) in enumerate(keep):
            Ls[j] = mol["consensus_length"]
            r1n[j] = mol["r1_is_negative"]
            r2n[j] = mol["r2_is_negative"]
            for side, en in ((0, en1), (1, en2)):
                if len(en) == 3:
                    slot_j[side].append(j)
                    slot_row[side].append(en[1])
                    slot_k[side].append(en[2])
                else:
                    arr_items.append((side, j, en))
        offs = np.zeros(J + 1, dtype=np.int64)
        np.cumsum(Ls, out=offs[1:])
        T = int(offs[-1])

        # oriented + padded strands (pad = lowercase n / Q0 / depth 0)
        b1 = np.full(T, NO_CALL_BASE_LOWER, np.uint8)
        b2 = np.full(T, NO_CALL_BASE_LOWER, np.uint8)
        q1 = np.zeros(T, np.uint8)
        q2 = np.zeros(T, np.uint8)
        # int32: every value here is pre-capped at I16_MAX, and the combine's
        # sums stay well under 2^31 — int64 was pure memory traffic
        d1 = np.zeros(T, np.int32)
        d2 = np.zeros(T, np.int32)
        e1 = np.zeros(T, np.int32)
        e2 = np.zeros(T, np.int32)

        def place_arr(bases_c, quals, dep, err, rc, pad_left, o, L,
                      b, q, d, e):
            bases = CODE_TO_BASE[np.minimum(bases_c, N_CODE)]
            k = len(bases)
            sl = slice(o + L - k, o + L) if pad_left else slice(o, o + k)
            if rc:
                b[sl] = _ASCII_COMPLEMENT[bases[::-1]]
                q[sl] = quals[::-1]
                d[sl] = np.minimum(dep[::-1], I16_MAX)
                e[sl] = np.minimum(err[::-1], I16_MAX)
            else:
                b[sl] = bases
                q[sl] = quals
                d[sl] = np.minimum(dep, I16_MAX)
                e[sl] = np.minimum(err, I16_MAX)

        def place_side(side, bt, qt, dt, et):
            """One side's placement: slot-backed strands in one vectorized
            gather+scatter; array-backed strands scalarly (collected by the
            single pass above)."""
            for aside, j, en in arr_items:
                if aside != side:
                    continue
                rc = r1n[j] if side == 0 else not r1n[j]
                pl = r1n[j] if side == 0 else r2n[j]
                place_arr(en[0], en[1], en[2], en[3], bool(rc), bool(pl),
                          int(offs[j]), int(Ls[j]), bt, qt, dt, et)
            if not slot_j[side]:
                return
            b_all, q_all, dmat, emat = slot_mats
            jarr = np.asarray(slot_j[side], np.int64)
            rows = np.asarray(slot_row[side], np.int64)
            ks = np.asarray(slot_k[side], np.int64)
            os_ = offs[jarr]
            rcs = r1n[jarr] if side == 0 else ~r1n[jarr]
            pls = r1n[jarr] if side == 0 else r2n[jarr]
            base = os_ + np.where(pls, Ls[jarr] - ks, 0)
            n_obs = int(ks.sum())
            within = np.arange(n_obs, dtype=np.int64) \
                - np.repeat(np.concatenate(([0], np.cumsum(ks)[:-1]))
                            if len(ks) else np.zeros(0, np.int64), ks)
            tgt = np.repeat(base, ks) + within
            rc_rep = np.repeat(rcs, ks)
            src_col = np.where(rc_rep, np.repeat(ks, ks) - 1 - within,
                               within)
            src_row = np.repeat(rows, ks)
            bb = CODE_TO_BASE[np.minimum(b_all[src_row, src_col], N_CODE)]
            bt[tgt] = np.where(rc_rep, _ASCII_COMPLEMENT[bb], bb)
            qt[tgt] = q_all[src_row, src_col]
            dt[tgt] = np.minimum(dmat[src_row, src_col], I16_MAX)
            et[tgt] = np.minimum(emat[src_row, src_col], I16_MAX)

        place_side(0, b1, q1, d1, e1)
        place_side(1, b2, q2, d2, e2)

        # ---- duplex combine, one pass over the concatenated strands:
        # device jit (ops/kernel._codec_combine_jit), native C pass, or
        # numpy — all byte-identical (the classic combine_arrays stays the
        # oracle). The concordance stage routes per batch through the
        # shared adaptive-stage runner (FGUMI_TPU_CODEC_COMBINE).
        import os

        kernel = caller.ss.kernel
        comb_env = os.environ.get("FGUMI_TPU_CODEC_COMBINE",
                                  "auto").strip().lower()

        def _host_combine():
            if nb.available():
                return nb.codec_combine(
                    b1, b2, q1, q2, d1, d2, e1, e2, MIN_PHRED, NO_CALL_BASE,
                    NO_CALL_BASE_LOWER, I16_MAX)
            return combine_arrays(b1, b2, q1, q2, d1, d2, e1, e2)

        if T > 0 and comb_env != "host" and not kernel.host_mode():
            from ..ops.kernel import codec_combine_device
            from ..ops.router import CODEC_COMBINE, run_adaptive_stage

            res, _side = run_adaptive_stage(
                CODEC_COMBINE, T, comb_env,
                lambda: codec_combine_device(b1, b2, q1, q2, d1, d2,
                                             e1, e2, mesh=self.mesh),
                _host_combine)
        else:
            res = _host_combine()
        cb, cq, cd, ce, both, disag = res

        # per-molecule disagreement thresholds (recoverable rejects)
        def seg_sum(x):
            cs = np.zeros(T + 1, np.int64)
            np.cumsum(x, out=cs[1:])
            return cs[offs[1:]] - cs[offs[:-1]]

        duplex_bases = seg_sum(both)
        disagreements = seg_sum(disag)
        st.consensus_duplex_bases_emitted += int(duplex_bases.sum())
        st.duplex_disagreement_base_count += int(disagreements.sum())
        nz = duplex_bases > 0
        bad = np.zeros(J, dtype=bool)
        if opts.max_duplex_disagreements is not None:
            bad |= nz & (disagreements > opts.max_duplex_disagreements)
        rate = np.divide(disagreements.astype(np.float64), duplex_bases,
                         out=np.zeros(J, np.float64), where=nz)
        bad |= nz & (rate > opts.max_duplex_disagreement_rate)

        # ---- quality masks (codec.py _mask_quals: outer bands, then SS)
        if (opts.outer_bases_length > 0
                and opts.outer_bases_qual is not None) \
                or opts.single_strand_qual is not None:
            if opts.outer_bases_length > 0 \
                    and opts.outer_bases_qual is not None:
                pos = np.arange(T, dtype=np.int64) \
                    - np.repeat(offs[:-1], Ls)
                l_rep = np.repeat(Ls, Ls)
                n_rep = np.minimum(opts.outer_bases_length, l_rep)
                cq[(pos < n_rep) | (pos >= l_rep - n_rep)] = \
                    opts.outer_bases_qual
            if opts.single_strand_qual is not None:
                is_n = lambda x: ((x == NO_CALL_BASE)
                                  | (x == NO_CALL_BASE_LOWER))
                cq[is_n(b1) | is_n(b2)] = opts.single_strand_qual

        # ---- record serialization
        good = []
        for j, (mol, _, _) in enumerate(keep):
            if bad[j]:
                st.reject("HighDuplexDisagreement",
                          mol["n_r1"] + mol["n_r2"])
                st.consensus_reads_rejected_hdd += 1
            else:
                good.append(j)
        if not good:
            return []

        if opts.cell_tag is not None:
            # rare option: the cell tag needs per-record raw scans, so build
            # through the classic RecordBuilder path
            out = []
            for j in good:
                mol = keep[j][0]
                sl = slice(int(offs[j]), int(offs[j] + Ls[j]))
                rc = mol["r1_is_negative"]

                def ss_of(b, q, d, e, count):
                    if rc:
                        return _SS(_ASCII_COMPLEMENT[b[sl][::-1]],
                                   q[sl][::-1], d[sl][::-1], e[sl][::-1],
                                   count)
                    return _SS(b[sl], q[sl], d[sl], e[sl], count)

                rec = caller._build_record(
                    ss_of(cb, cq, cd, ce, mol["n_r1"] + mol["n_r2"]),
                    ss_of(b1, q1, d1, e1, mol["n_r1"]),
                    ss_of(b2, q2, d2, e2, mol["n_r2"]),
                    mol["umi"], mol["source_raws"], mol["records"],
                    rx_umis=mol.get("rx_umis"))
                out.append(struct.pack("<I", len(rec)) + rec)
            return out

        return self._serialize_native(keep, good, offs, Ls, r1n, cb, cq,
                                      np.ascontiguousarray(ce,
                                                           dtype=np.int64),
                                      b1, q1, d1, e1, b2, q2, d2, e2)

    def _serialize_native(self, keep, good, offs, Ls, r1n, cb, cq, ce,
                          b1, q1, d1, e1, b2, q2, d2, e2):
        """One native serialization pass (codec.py _build_record byte-exact).

        The final reverse-complement for r1-negative molecules is a single
        vectorized gather (consensus errors stay unreversed: they only feed
        the cE sum and have no per-base tag); names/MI/RX pack into one blob
        and all rows pass to C as raw addresses.
        """
        from .simple_umi import consensus_umis_batch

        caller = self.caller
        st, opts = caller.stats, caller.options
        T = int(offs[-1])
        pos = np.arange(T, dtype=np.int64) - np.repeat(offs[:-1], Ls)
        rc_rep = np.repeat(r1n, Ls)
        src = np.where(rc_rep,
                       np.repeat(offs[:-1] + Ls - 1, Ls) - pos,
                       np.arange(T, dtype=np.int64))

        def gath(a, comp=False, dtype=None):
            # dtype=int64 where the native builder reads 8-byte elements
            # (the combine math upstream runs in int32; widening costs a
            # second copy of the gathered temp, cheap next to the combine)
            g = np.ascontiguousarray(a[src], dtype=dtype)
            if comp:
                g[rc_rep] = _ASCII_COMPLEMENT[g[rc_rep]]
            return g

        seq = gath(cb, comp=True)
        qual = gath(cq)
        a_b = gath(b1, comp=True)
        a_q = gath(q1)
        a_d = gath(d1, dtype=np.int64)
        a_e = gath(e1, dtype=np.int64)
        b_b = gath(b2, comp=True)
        b_q = gath(q2)
        b_d = gath(d2, dtype=np.int64)
        b_e = gath(e2, dtype=np.int64)

        # RX consensus per molecule, all non-trivial families in one pass
        fams = []
        for j in good:
            mol = keep[j][0]
            ru = mol.get("rx_umis")
            if ru is None:  # classic-prepared molecule: scan its records
                ru = [u for u in (r.get_str(b"RX") for r in mol["records"])
                      if u]
            fams.append(ru)
        nonempty = [i for i, f in enumerate(fams) if f]
        consensi = consensus_umis_batch([fams[i] for i in nonempty]) \
            if nonempty else []
        rx_strs = [None] * len(fams)
        for i, cu in zip(nonempty, consensi):
            if cu:
                rx_strs[i] = cu.encode()

        # names / MI / RX share one blob; addresses point into it
        G = len(good)
        blob = bytearray()
        name_off = np.empty(G, np.int64)
        name_len = np.empty(G, np.int32)
        mi_off = np.zeros(G, np.int64)
        mi_len = np.full(G, -1, np.int32)
        rx_off = np.zeros(G, np.int64)
        rx_len = np.zeros(G, np.int32)
        prefix = caller.prefix
        for k, j in enumerate(good):
            umi = keep[j][0]["umi"]
            caller._counter += 1
            name = (f"{prefix}:{umi}" if umi
                    else f"{prefix}:{caller._counter}").encode()
            name_off[k] = len(blob)
            name_len[k] = len(name)
            blob.extend(name)
            if umi:
                mi = umi.encode()
                mi_off[k] = len(blob)
                mi_len[k] = len(mi)
                blob.extend(mi)
            if rx_strs[k] is not None:
                rx_off[k] = len(blob)
                rx_len[k] = len(rx_strs[k])
                blob.extend(rx_strs[k])
        blob_arr = np.frombuffer(bytes(blob), dtype=np.uint8)
        base = blob_arr.ctypes.data if len(blob_arr) else 0

        gi = np.asarray(good, dtype=np.int64)
        og = offs[:-1][gi]
        wire, rec_end = nb.build_codec_records(
            seq.ctypes.data + og, qual.ctypes.data + og,
            ce.ctypes.data + 8 * og,
            a_b.ctypes.data + og, a_q.ctypes.data + og,
            a_d.ctypes.data + 8 * og, a_e.ctypes.data + 8 * og,
            b_b.ctypes.data + og, b_q.ctypes.data + og,
            b_d.ctypes.data + 8 * og, b_e.ctypes.data + 8 * og,
            Ls[gi], base + name_off, name_len,
            np.where(mi_len >= 0, base + mi_off, 0), mi_len,
            np.where(rx_len > 0, base + rx_off, 0), rx_len,
            caller.read_group_id.encode(), FLAG_UNMAPPED,
            opts.produce_per_base_tags)
        st.consensus_reads_generated += G
        return [wire]  # records carry their block_size prefixes

    # ---------------------------------------------------------------- prepare

    def _prepare_span(self, batch, bounds, g0, g1):
        """Vectorized prepare for complete groups [g0, g1); shape-ineligible
        molecules run the classic prepare in stream order."""
        caller = self.caller
        buf = batch.buf
        lo, hi = int(bounds[g0]), int(bounds[g1])
        span = np.arange(lo, hi)
        flag = batch.flag
        l_seq = batch.l_seq

        # single-op all-M CIGAR covering the whole read
        co = batch.cigar_off
        v = np.zeros(len(span), dtype=np.uint32)
        for j in range(4):
            v |= buf[co[span] + j].astype(np.uint32) << (8 * j)
        m_only = ((batch.n_cigar[span] == 1) & ((v & 0xF) == 0)
                  & ((v >> 4) == l_seq[span]) & (l_seq[span] > 0))
        fl = flag[span]
        paired_primary = ((fl & FLAG_PAIRED) != 0) \
            & ((fl & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY)) == 0)
        row_ok = m_only | ~paired_primary
        g_of_row = np.repeat(np.arange(g1 - g0),
                             np.diff(bounds[g0:g1 + 1]))
        grp_ok = np.ones(g1 - g0, dtype=bool)
        np.logical_and.at(grp_ok, g_of_row, row_ok)

        # phases 1-2 (primary-pair formation by name + clip closed forms)
        # run once over the whole eligible span, then phases 3-4 (overlap
        # geometry + verdicts) as one array pass (_geometry_vec);
        # hash-collision groups fall back to the per-molecule python
        # pairing, downsampled groups to the per-molecule geometry
        pair_of_group, py_groups, geom = self._pair_span(
            batch, span, g_of_row, grp_ok, fl, paired_primary)

        # bulk pack layout of the geometry-ok groups occupies [0, pk_base);
        # per-molecule fallbacks append after it
        st = caller.stats
        nG = g1 - g0
        loc = np.full(nG, -1, dtype=np.int64)
        if geom is not None:
            loc[geom["gid"]] = np.arange(len(geom["gid"]))
        pk_base = len(geom["pack0"]) if geom is not None else 0

        mols = []
        pack_rows = []     # per-molecule fallback rows, after the bulk block
        pack_clips = []
        pending = []       # (kind, payload) preserving stream order
        for g in range(g0, g1):
            rows = np.arange(int(bounds[g]), int(bounds[g + 1]))
            mi = batch.tag_bytes(self.tag, int(rows[0])).decode()
            if not grp_ok[g - g0]:
                # classic prepare runs HERE, in stream order — the shared
                # downsample RNG stream must see molecules in input order
                mol = caller.prepare(batch.raw_records(rows), umi=mi)
                pending.append(("mol", mol) if mol is not None
                               else ("none", None))
                continue
            if (g - g0) in py_groups:
                prep = self._prepare_molecule_vec(batch, rows, mi, pack_rows,
                                                  pack_clips, pk_base)
            else:
                k = int(loc[g - g0])
                if k < 0:
                    prep = None  # no surviving FR pair in this group
                elif geom["small"][k]:
                    st.reject("InsufficientReads", 2 * int(geom["n_g"][k]))
                    prep = None
                elif geom["downs"][k]:
                    # downsample consumes the shared RNG stream — the
                    # per-molecule reference path runs, in stream order
                    prep = self._finish_molecule_vec(
                        rows, mi, pair_of_group.get(g - g0), pack_rows,
                        pack_clips, pk_base)
                elif geom["short"][k]:
                    st.reject("InsufficientOverlap", 2 * int(geom["n_g"][k]))
                    prep = None
                elif geom["indel"][k]:
                    st.reject("IndelErrorBetweenStrands",
                              2 * int(geom["n_g"][k]))
                    prep = None
                else:
                    s_, e_ = int(geom["starts"][k]), int(geom["ends"][k])
                    prep = {
                        "mi": mi, "rows": rows,
                        "pk0": int(geom["pk0_seg"][k]),
                        "r1_rows": geom["r1"][s_:e_],
                        "r2_rows": geom["r2"][s_:e_],
                        "r1_flens": geom["flen1"][s_:e_],
                        "r2_flens": geom["flen2"][s_:e_],
                        "r1_neg": bool(geom["r1_neg"][k]),
                        "r2_neg": bool(geom["r2_neg"][k]),
                        "consensus_length":
                            int(geom["consensus_length"][k]),
                    }
            pending.append(("vec", prep) if prep is not None
                           else ("none", None))

        codes_pk = quals_pk = None
        if pk_base or pack_rows:
            parts_r = []
            parts_c = []
            if pk_base:
                parts_r.append(geom["pack0"])
                parts_c.append(geom["clips0"])
            if pack_rows:
                parts_r.append(np.asarray(pack_rows, dtype=np.int64))
                parts_c.append(np.asarray(pack_clips, dtype=np.int64))
            rows_arr = np.concatenate(parts_r)
            clips_arr = np.concatenate(parts_c)
            stride = max(-(-int(l_seq[rows_arr].max()) // 32) * 32, 32)
            rev = ((flag[rows_arr] & FLAG_REVERSE) != 0).astype(np.uint8)
            codes_pk, quals_pk, _ = nb.pack_reads(
                buf, np.ascontiguousarray(batch.seq_off[rows_arr]),
                np.ascontiguousarray(batch.qual_off[rows_arr]),
                l_seq[rows_arr], rev,
                clips_arr.astype(np.int32), 0, stride, mode=3)

        for item in pending:
            if item[0] == "mol":
                mols.append(item[1])
            elif item[0] == "vec":
                mols.append(self._finalize_vec(batch, item[1]))
        return [m for m in mols if m is not None], codes_pk, quals_pk

    def _pair_span(self, batch, span, g_of_row, grp_ok, fl_span, pp_span):
        """Phases 1-2 for every eligible group in one pass: primary FR
        pairing by read name (FNV hash buckets, byte-verified) plus the
        clip/adjusted-position closed forms, all as span-wide array math.
        fl_span / pp_span are the caller's per-span flag values and
        paired-primary mask (shared, not recomputed).

        Returns ({local_g: per-pair arrays}, {local_g needing the python
        pairing}). The second set holds groups where two distinct names
        share a hash (byte-verify failed) — their stats are untouched here
        so the per-molecule path recounts them exactly.
        """
        st = self.caller.stats
        flag = batch.flag
        l_seq = batch.l_seq
        pos = batch.pos
        buf = batch.buf
        elig = grp_ok[g_of_row]
        rows = span[elig]
        g_of = g_of_row[elig]
        if len(rows) == 0:
            return {}, set(), None

        paired = (fl_span[elig] & FLAG_PAIRED) != 0
        ppm = pp_span[elig]
        pr = rows[ppm]
        pg = g_of[ppm]

        # name buckets within each group (classic by_name first-appearance
        # dict, fast_codec _prepare_molecule_vec phase 2)
        noff = (batch.data_off[pr] + 32).astype(np.int64)
        nlen = batch.l_read_name[pr].astype(np.int32) - 1
        h = nb.hash_ranges(buf, noff, nlen)
        order = np.lexsort((np.arange(len(pr)), h, pg))
        sp, sg, sh = pr[order], pg[order], h[order]
        so, sno, snl = order, noff[order], nlen[order]
        new_b = np.ones(len(sp), dtype=bool)
        if len(sp) > 1:
            new_b[1:] = (sg[1:] != sg[:-1]) | (sh[1:] != sh[:-1])
        b_start = np.nonzero(new_b)[0]
        b_size = np.diff(np.append(b_start, len(sp)))
        # collision guard: every bucket member must byte-match its head
        head = np.repeat(b_start, b_size)
        same = nb.ranges_equal(buf, sno, snl, sno[head], snl[head])
        py_groups = set(int(g) for g in np.unique(sg[same == 0]))

        ok_mask = np.ones(len(b_start), dtype=bool)
        if py_groups:
            bg_all = sg[b_start]
            ok_mask = ~np.isin(bg_all, np.fromiter(py_groups, dtype=sg.dtype,
                                                   count=len(py_groups)))
        # stats for the groups resolved here (python-fallback groups excluded)
        if py_groups:
            keep_rows = ~np.isin(g_of, np.fromiter(py_groups, dtype=g_of.dtype,
                                                   count=len(py_groups)))
            st.total_input_reads += int(keep_rows.sum())
            frag = int((~paired[keep_rows]).sum())
        else:
            st.total_input_reads += len(rows)
            frag = int((~paired).sum())
        if frag:
            st.reject("FragmentRead", frag)

        two = ok_mask & (b_size == 2)
        odd_total = int(b_size[ok_mask & ~two].sum())

        ia = sp[b_start[two]]
        ib = sp[b_start[two] + 1]
        bg = sg[b_start[two]]
        first_orig = so[b_start[two]]  # classic bucket order: name appearance

        # is_primary_fr_pair, vectorized (overlap.py:96-156 for all-M rows)
        fa, fb = flag[ia], flag[ib]
        ok = ((fa | fb) & (FLAG_UNMAPPED | FLAG_MATE_UNMAPPED)) == 0
        ok &= batch.ref_id[ia] == batch.ref_id[ib]
        a_rev = (fa & FLAG_REVERSE) != 0
        ok &= a_rev != ((fb & FLAG_REVERSE) != 0)
        r = np.where(a_rev, ia, ib)
        rf = flag[r]
        ok &= batch.ref_id[r] == batch.next_ref_id[r]
        ok &= ((rf & FLAG_REVERSE) != 0) != ((rf & FLAG_MATE_REVERSE) != 0)
        start = pos[r].astype(np.int64) + 1
        mate_start = batch.next_pos[r].astype(np.int64) + 1
        rrev = (rf & FLAG_REVERSE) != 0
        end = start + np.maximum(l_seq[r].astype(np.int64) - 1, 0)
        pos5 = np.where(rrev, mate_start, start)
        neg5 = np.where(rrev, end, start + batch.tlen[r].astype(np.int64))
        ok &= pos5 < neg5

        n_failed = int((~ok).sum())
        if odd_total or n_failed:
            st.reject("NotPrimaryFrPair", odd_total + 2 * n_failed)

        ia, ib, bg, first_orig = ia[ok], ib[ok], bg[ok], first_orig[ok]
        a_first = (flag[ia] & FLAG_FIRST) != 0
        r1 = np.where(a_first, ia, ib)
        r2 = np.where(a_first, ib, ia)

        # clip_vs closed forms, both directions (all-M geometry)
        def clips(ra, rb):
            ms = pos[rb].astype(np.int64) + 1
            me = pos[rb].astype(np.int64) + l_seq[rb]
            p1 = pos[ra].astype(np.int64) + 1
            L = l_seq[ra].astype(np.int64)
            d = ms - p1
            c_rev = np.where((p1 <= ms) & (d < L), d, 0)
            end1 = p1 - 1 + L
            bp = np.where((me < p1) | (me >= p1 + L), 0, me - p1 + 1)
            c_fwd = np.where(end1 >= me, np.maximum(L - bp, 0), 0)
            return np.where((flag[ra] & FLAG_REVERSE) != 0, c_rev, c_fwd)

        def info(rr, clip):
            rev = (flag[rr] & FLAG_REVERSE) != 0
            L = l_seq[rr].astype(np.int64)
            flen = np.maximum(L - clip, 0)
            adj = pos[rr].astype(np.int64) + 1 \
                + np.where(rev, np.minimum(clip, L), 0)
            return clip.astype(np.int64), rev, flen, adj

        c1, rev1, flen1, adj1 = info(r1, clips(r1, r2))
        c2, rev2, flen2, adj2 = info(r2, clips(r2, r1))

        # classic pair order within a group = first appearance of the name
        po = np.lexsort((first_orig, bg))
        arrs = (r1[po], c1[po], rev1[po], flen1[po], adj1[po],
                r2[po], c2[po], rev2[po], flen2[po], adj2[po])
        bg = bg[po]
        geom = self._geometry_vec(arrs, bg)
        # per-group pair tuples only for the groups that still take the
        # per-molecule path (downsampling consumes the shared RNG stream);
        # slicing them for every group was a measurable per-group loop
        out = {}
        if geom is not None and geom["downs"].any():
            starts, ends, gid = geom["starts"], geom["ends"], geom["gid"]
            for k in np.nonzero(geom["downs"])[0]:
                out[int(gid[k])] = tuple(a[starts[k]:ends[k]] for a in arrs)
        return out, py_groups, geom

    def _geometry_vec(self, arrs, bg):
        """Phases 3-4 for EVERY paired group in one array pass: the
        per-group verdict (ok / too-small / short-overlap / indel /
        needs-per-molecule-downsample), the overlap geometry of the ok
        groups, and their bulk pack layout (r1 block then r2 block per
        group, group order) — semantically identical to running
        _finish_molecule_vec per group, which remains the reference
        implementation used by the downsample fallback."""
        P = len(bg)
        if P == 0:
            return None
        opts = self.caller.options
        (r1, c1, rev1, flen1, adj1, r2, c2, rev2, flen2, adj2) = arrs
        starts = np.nonzero(np.concatenate(([True], bg[1:] != bg[:-1])))[0]
        ends = np.append(starts[1:], P)
        gid = bg[starts]
        nseg = len(gid)
        n_g = ends - starts
        seg_of_pair = np.repeat(np.arange(nseg), n_g)

        # first-occurrence argmax of each strand's clipped lengths
        pidx = np.arange(P)
        m1 = np.maximum.reduceat(flen1, starts)
        i1 = np.minimum.reduceat(
            np.where(flen1 == m1[seg_of_pair], pidx, P), starts)
        m2 = np.maximum.reduceat(flen2, starts)
        i2 = np.minimum.reduceat(
            np.where(flen2 == m2[seg_of_pair], pidx, P), starts)

        r1_neg = rev1[i1]
        r2_neg = rev2[i2]
        L1f, L1a = flen1[i1], adj1[i1]
        L2f, L2a = flen2[i2], adj2[i2]
        Lpf = np.where(r1_neg, L2f, L1f)
        Lpa = np.where(r1_neg, L2a, L1a)
        Lnf = np.where(r1_neg, L1f, L2f)
        Lna = np.where(r1_neg, L1a, L2a)
        overlap_start = Lna
        pos_end = Lpa + np.maximum(Lpf - 1, 0)
        duplex_length = pos_end - overlap_start + 1

        def rp(adj, cl, p):
            return p - adj + 1, (adj <= p) & (p <= adj + cl - 1)

        r1s, ok1s = rp(L1a, L1f, overlap_start)
        r2s, ok2s = rp(L2a, L2f, overlap_start)
        r1e, ok1e = rp(L1a, L1f, pos_end)
        r2e, ok2e = rp(L2a, L2f, pos_end)
        pv, okp = rp(Lpa, Lpf, pos_end)
        nv, okn = rp(Lna, Lnf, pos_end)
        indel = ~(ok1s & ok2s & ok1e & ok2e) \
            | ((r1s - r2s) != (r1e - r2e)) | ~okp | ~okn
        consensus_length = pv + Lnf - nv

        small = n_g < opts.min_reads_per_strand
        max_pairs = opts.max_reads_per_strand
        downs = (n_g > max_pairs) & ~small if max_pairs is not None \
            else np.zeros(nseg, dtype=bool)
        short = duplex_length < opts.min_duplex_length
        okg = ~small & ~downs & ~short & ~indel

        # bulk pack layout for ok groups: [r1 block, r2 block] per group
        n_s = n_g[okg]
        excl = (np.concatenate(([0], np.cumsum(n_s)[:-1]))
                if len(n_s) else np.zeros(0, np.int64)).astype(np.int64)
        off = 2 * excl
        pk0_seg = np.full(nseg, -1, dtype=np.int64)
        pk0_seg[okg] = off
        total = int(2 * n_s.sum())
        sel = okg[seg_of_pair]
        within = np.arange(int(n_s.sum()), dtype=np.int64) \
            - np.repeat(excl, n_s)
        r1_t = np.repeat(off, n_s) + within
        r2_t = np.repeat(off + n_s, n_s) + within
        pack0 = np.empty(total, dtype=np.int64)
        clips0 = np.empty(total, dtype=np.int64)
        pack0[r1_t] = r1[sel]
        pack0[r2_t] = r2[sel]
        clips0[r1_t] = c1[sel]
        clips0[r2_t] = c2[sel]

        return {"gid": gid, "starts": starts, "ends": ends,
                "n_g": n_g, "small": small, "downs": downs, "short": short,
                "indel": indel, "okg": okg, "r1_neg": r1_neg,
                "r2_neg": r2_neg, "consensus_length": consensus_length,
                "pk0_seg": pk0_seg, "pack0": pack0, "clips0": clips0,
                "r1": r1, "r2": r2, "flen1": flen1, "flen2": flen2}

    def _finish_molecule_vec(self, rows, mi, pairs, pack_rows, pack_clips,
                             pk_base=0):
        """Phases 3-5 for one group given its span-paired arrays; returns a
        partial mol (pack rows staged) or None with classic reject stats."""
        caller = self.caller
        st = caller.stats
        opts = caller.options
        if pairs is None:  # no surviving FR pair in this group
            return None
        (r1, c1, rev1, flen1, adj1, r2, c2, rev2, flen2, adj2) = pairs
        n = len(r1)
        if n < opts.min_reads_per_strand:
            st.reject("InsufficientReads", 2 * n)
            return None
        max_pairs = opts.max_reads_per_strand
        if max_pairs is not None and n > max_pairs:
            idxs = np.sort(caller._rng.permutation(n)[:max_pairs])
            (r1, c1, rev1, flen1, adj1, r2, c2, rev2, flen2, adj2) = (
                a[idxs] for a in pairs)
            n = max_pairs
        n_filtered = 2 * n

        # phase 4: overlap geometry on the longest strands (first max)
        i1, i2 = int(np.argmax(flen1)), int(np.argmax(flen2))
        r1_neg, r2_neg = bool(rev1[i1]), bool(rev2[i2])
        L1 = (int(flen1[i1]), int(adj1[i1]))
        L2 = (int(flen2[i2]), int(adj2[i2]))
        Lpos, Lneg = (L2, L1) if r1_neg else (L1, L2)
        overlap_start = Lneg[1]
        pos_end = Lpos[1] + max(Lpos[0] - 1, 0)
        duplex_length = pos_end - overlap_start + 1
        if duplex_length < opts.min_duplex_length:
            st.reject("InsufficientOverlap", n_filtered)
            return None

        def rp(i, p):
            flen, adj = i
            if adj <= p <= adj + flen - 1:
                return p - adj + 1
            return None

        r1s, r2s = rp(L1, overlap_start), rp(L2, overlap_start)
        r1e, r2e = rp(L1, pos_end), rp(L2, pos_end)
        if None in (r1s, r2s, r1e, r2e) or (r1s - r2s) != (r1e - r2e):
            st.reject("IndelErrorBetweenStrands", n_filtered)
            return None
        p = rp(Lpos, pos_end)
        n_ = rp(Lneg, pos_end)
        if p is None or n_ is None:
            st.reject("IndelErrorBetweenStrands", n_filtered)
            return None
        consensus_length = p + Lneg[0] - n_

        pk0 = pk_base + len(pack_rows)
        pack_rows.extend(r1.tolist())
        pack_clips.extend(c1.tolist())
        pack_rows.extend(r2.tolist())
        pack_clips.extend(c2.tolist())
        return {
            "mi": mi, "rows": rows, "pk0": pk0,
            "r1_rows": r1, "r2_rows": r2,
            "r1_flens": flen1, "r2_flens": flen2,
            "r1_neg": r1_neg, "r2_neg": r2_neg,
            "consensus_length": consensus_length,
        }

    def _prepare_molecule_vec(self, batch, rows, mi, pack_rows, pack_clips,
                              pk_base=0):
        """Phases 1-4 on arrays; returns a partial mol (pack indices staged)
        or None (rejected, reasons recorded like classic prepare)."""
        caller = self.caller
        st = caller.stats
        opts = caller.options
        flag = batch.flag
        l_seq = batch.l_seq
        pos = batch.pos
        st.total_input_reads += len(rows)

        fl = flag[rows]
        frag = int(((fl & FLAG_PAIRED) == 0).sum())
        if frag:
            st.reject("FragmentRead", frag)
        pp = rows[((fl & FLAG_PAIRED) != 0)
                  & ((fl & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY)) == 0)]
        if len(pp) == 0:
            return None

        # phase 2: first-appearance name buckets, one FR pair per template
        by_name = {}
        for k in range(len(pp)):
            by_name.setdefault(batch.name(int(pp[k])), []).append(k)
        pairs = []  # (r1_row, r2_row)
        for name, bucket in by_name.items():
            if len(bucket) != 2 or not self._is_primary_fr_pair(
                    batch, int(pp[bucket[0]]), int(pp[bucket[1]])):
                st.reject("NotPrimaryFrPair", len(bucket))
                continue
            ra, rb = int(pp[bucket[0]]), int(pp[bucket[1]])
            pairs.append((ra, rb) if flag[ra] & FLAG_FIRST else (rb, ra))
        if not pairs:
            return None
        if len(pairs) < opts.min_reads_per_strand:
            st.reject("InsufficientReads", 2 * len(pairs))
            return None

        max_pairs = opts.max_reads_per_strand
        if max_pairs is not None and len(pairs) > max_pairs:
            idxs = sorted(caller._rng.permutation(len(pairs))[:max_pairs])
            pairs = [pairs[i] for i in idxs]

        # clip + adjusted position + clipped length (all-M closed forms)
        def clip_vs(ra, rb):
            ms = pos[rb] + 1
            me = pos[rb] + l_seq[rb]
            p1 = pos[ra] + 1
            L = int(l_seq[ra])
            if flag[ra] & FLAG_REVERSE:
                if p1 <= ms:
                    d = int(ms - p1)
                    return d if d < L else 0
                return 0
            end1 = p1 - 1 + L
            if end1 >= me:
                if me < p1 or me >= p1 + L:
                    bp = 0
                else:
                    bp = int(me - p1 + 1)
                return max(L - bp, 0)
            return 0

        def info(r, clip):
            rev = bool(flag[r] & FLAG_REVERSE)
            ref_consumed = min(clip, int(l_seq[r]))
            adj = int(pos[r]) + 1 + (ref_consumed if rev else 0)
            return (r, clip, rev, max(int(l_seq[r]) - clip, 0), adj)

        r1i = []
        r2i = []
        for ra, rb in pairs:
            r1i.append(info(ra, clip_vs(ra, rb)))
            r2i.append(info(rb, clip_vs(rb, ra)))
        # phase 3 (most-common-alignment filter): single-op M CIGARs always
        # form one prefix-compatible group -> keep all, no rejects
        n_filtered = len(r1i) + len(r2i)

        # phase 4: overlap geometry on the longest strands (first max)
        cl1 = np.array([i[3] for i in r1i])
        cl2 = np.array([i[3] for i in r2i])
        L1 = r1i[int(np.argmax(cl1))]
        L2 = r2i[int(np.argmax(cl2))]
        r1_neg, r2_neg = L1[2], L2[2]
        Lpos, Lneg = (L2, L1) if r1_neg else (L1, L2)
        overlap_start = Lneg[4]
        pos_end = Lpos[4] + max(Lpos[3] - 1, 0)
        duplex_length = pos_end - overlap_start + 1
        if duplex_length < opts.min_duplex_length:
            st.reject("InsufficientOverlap", n_filtered)
            return None

        def rp(i, p):
            adj, cl = i[4], i[3]
            if adj <= p <= adj + cl - 1:
                return p - adj + 1
            return None

        r1s, r2s = rp(L1, overlap_start), rp(L2, overlap_start)
        r1e, r2e = rp(L1, pos_end), rp(L2, pos_end)
        if None in (r1s, r2s, r1e, r2e) or (r1s - r2s) != (r1e - r2e):
            st.reject("IndelErrorBetweenStrands", n_filtered)
            return None
        p = rp(Lpos, pos_end)
        n_ = rp(Lneg, pos_end)
        if p is None or n_ is None:
            st.reject("IndelErrorBetweenStrands", n_filtered)
            return None
        consensus_length = p + Lneg[3] - n_

        # stage the pack rows (r1 strand then r2 strand, pair order)
        pk0 = pk_base + len(pack_rows)
        for i in r1i:
            pack_rows.append(i[0])
            pack_clips.append(i[1])
        for i in r2i:
            pack_rows.append(i[0])
            pack_clips.append(i[1])
        return {
            "mi": mi, "rows": rows, "pk0": pk0,
            "r1_rows": np.array([i[0] for i in r1i], dtype=np.int64),
            "r2_rows": np.array([i[0] for i in r2i], dtype=np.int64),
            "r1_flens": np.array([i[3] for i in r1i], dtype=np.int64),
            "r2_flens": np.array([i[3] for i in r2i], dtype=np.int64),
            "r1_neg": r1_neg, "r2_neg": r2_neg,
            "consensus_length": consensus_length,
        }

    def _finalize_vec(self, batch, prep):
        """Phase 5: the mol dict for the dense dispatch in _run.

        No SS jobs are materialized — the strand rows stay resident in the
        span's pack arrays and _run gathers them directly (the SS caller's
        min_reads=1 / max_reads=None construction makes per-strand
        consensus_len = longest clipped read, carried via the flens).
        """
        caller = self.caller
        f1, f2 = prep["r1_flens"], prep["r2_flens"]
        umi = prep["mi"]
        if caller.options.cell_tag is not None:
            # only the cell-tag fallback reads raw records back
            records = batch.raw_records(prep["rows"])
            row_to_rec = {int(r): rec
                          for r, rec in zip(prep["rows"], records)}
            source_raws = [row_to_rec[int(r)] for r in
                           np.concatenate([prep["r1_rows"], prep["r2_rows"]])]
        else:
            records, source_raws = None, None
        # RX strings for the whole group from the batch tag scan (same Z/H
        # gate and lenient decode as RawRecord.get_str; codec.py RX consensus)
        rx_off, rx_len, _ = batch.tag_locs_str(b"RX")
        buf = batch.buf
        rx_umis = []
        for r in prep["rows"]:
            o, ln = int(rx_off[r]), int(rx_len[r])
            if o >= 0 and ln > 0:
                rx_umis.append(buf[o:o + ln].tobytes().decode(errors="replace"))
        return {
            "umi": umi, "records": records,
            "pk0": prep["pk0"], "r1_flens": f1, "r2_flens": f2,
            "n_r1": len(f1), "n_r2": len(f2),
            "r1_is_negative": prep["r1_neg"],
            "r2_is_negative": prep["r2_neg"],
            "consensus_length": prep["consensus_length"],
            "source_raws": source_raws,
            "rx_umis": rx_umis,
        }

    @staticmethod
    def _is_primary_fr_pair(batch, ia, ib):
        """is_primary_fr_pair + is_fr_pair for all-M records (overlap.py:96-156)."""
        flag = batch.flag
        fa, fb = int(flag[ia]), int(flag[ib])
        if (fa | fb) & (FLAG_UNMAPPED | FLAG_MATE_UNMAPPED):
            return False
        if batch.ref_id[ia] != batch.ref_id[ib]:
            return False
        a_rev = bool(fa & FLAG_REVERSE)
        if a_rev == bool(fb & FLAG_REVERSE):
            return False
        r = ia if a_rev else ib
        rf = int(flag[r])
        if batch.ref_id[r] != batch.next_ref_id[r]:
            return False
        if bool(rf & FLAG_REVERSE) == bool(rf & FLAG_MATE_REVERSE):
            return False
        # is_fr_pair on the reverse-strand record (M-only: ref_len == l_seq)
        start = int(batch.pos[r]) + 1
        mate_start = int(batch.next_pos[r]) + 1
        if rf & FLAG_REVERSE:
            end = start + max(int(batch.l_seq[r]) - 1, 0)
            positive_5p, negative_5p = mate_start, end
        else:
            positive_5p, negative_5p = start, start + int(batch.tlen[r])
        return positive_5p < negative_5p
