"""Device-resident consensus→filter fusion (ISSUE 11, ROADMAP §3).

The host-shaped pipeline pays the link twice for every filtered consensus
record: the full winner/qual/depth/errors columns are fetched home
(5.25 B/position), serialized, and then the filter command re-parses the
bytes just to drop most of them on a filter-heavy config. This module fuses
the two stages behind ``--device-filter``:

- stage 1 (ops/kernel._consensus_segments_wire_filter_jit) keeps the
  consensus columns **device-resident**, applies the consensus thresholds
  and the filter library's per-base masks as one fused kernel, and fetches
  only a 28 B/read stats row (max/total depth, total errors, qual sum,
  post-mask N count, newly-masked count, suspect flag);
- the host computes the per-read verdicts from those scalars with the SAME
  array helpers the batch filter engine uses (consensus/filter.py — one
  numeric core, so the fused route cannot drift from ``fgumi-tpu filter``);
- stage 2 gathers only the *surviving* records' masked columns home
  (ops/kernel.filter_gather_device) and the native serializer emits them —
  byte-identical to ``simplex | filter`` by construction.

Exactness contract: every floating-point comparison the host filter makes
is either (a) recomputed on host from exactly-fetched integer sums (cE,
mean quality, no-call fraction), or (b) reformulated as a pure integer
compare on device via :func:`consensus.filter.base_error_rate_table`.
Reads touching an oracle-suspect position fetch their raw columns and run
the ordinary host completion (oracle patch + host filter math). Degraded
device paths (deadline, transient failure, OOM halving) fall back to full
columns + the host filter pass — byte-identical like every other degrade.

The duplex/codec engines route ``--device-filter`` through
:class:`HostFilterTap` — the same in-process fusion (no intermediate BAM,
no re-parse by a second command) with the per-record reference filter;
their column-space device kernels are a follow-up (docs/device-datapath.md
"Device-resident filtering").
"""

import threading

import numpy as np

from ..constants import MIN_PHRED, N_CODE
from ..ops import oracle
from .filter import (PASS, R_PASS, RESULT_NAMES, FilterConfig,
                     base_error_rate_table, simplex_base_mask_arrays,
                     simplex_read_verdicts)

_I16_MAX = 32767


def device_filter_requested(args) -> bool:
    """CLI/env gate for the fused consensus→filter route."""
    import os

    if getattr(args, "device_filter", False):
        return True
    return os.environ.get("FGUMI_TPU_DEVICE_FILTER", "").strip().lower() \
        in ("1", "true", "on", "force")


def device_mask_enabled() -> bool:
    """Whether the fused per-base mask runs ON DEVICE (default yes).
    ``FGUMI_TPU_DEVICE_FILTER=0`` keeps the fused single-process stage but
    computes every mask host-side from fetched full columns — the A/B
    escape hatch for the reduced-fetch kernel."""
    import os

    return os.environ.get("FGUMI_TPU_DEVICE_FILTER", "").strip().lower() \
        not in ("0", "false", "off")


def filter_config_from_args(args) -> FilterConfig:
    """FilterConfig from the consensus commands' ``--filter-*`` options
    (same option grammar as the standalone ``filter`` command)."""
    return FilterConfig.new(
        [int(v) for v in str(args.filter_min_reads).split(",")],
        [float(v) for v in str(args.filter_max_read_error_rate).split(",")],
        [float(v) for v in str(args.filter_max_base_error_rate).split(",")],
        min_base_quality=args.filter_min_base_quality,
        min_mean_base_quality=args.filter_min_mean_base_quality,
        max_no_call_fraction=args.filter_max_no_call_fraction)


class DeviceFilterParams:
    """Device-side constants of the fused simplex mask kernel.

    Built once per run; the error-rate threshold table rides the constant
    cache (content-keyed) so repeated dispatches upload nothing."""

    __slots__ = ("min_reads", "emin_tab", "min_base_q", "per_base")

    def __init__(self, config: FilterConfig, produce_per_base_tags: bool):
        t = config.single_strand
        self.min_reads = np.int32(t.min_reads)
        self.emin_tab = base_error_rate_table(t.max_base_error_rate)
        self.min_base_q = np.int32(-1 if config.min_base_quality is None
                                   else int(config.min_base_quality))
        # mask_bases applies depth/error per-base masks only when the
        # record carries cd+ce tags — i.e. when the engine serializes them
        self.per_base = bool(produce_per_base_tags)


#: columns of the fused kernel's per-read stats fetch (int32 each)
S_MAXD, S_SUMD, S_SUME, S_QSUM, S_NAFTER, S_NEWLY, S_SUSPECT = range(7)
STATS_COLS = 7


def fused_stats_oracle(winner, qual, depth, errors, lens, min_reads_c,
                       min_qual_c, params: DeviceFilterParams):
    """Numpy twin of the fused kernel's threshold+filter epilogue
    (ops/kernel._wire_filter_fn) over PRE-threshold (J, L) columns.

    Built for the sentinel's fused-route audit (ops/sentinel.py): given
    the f64 host oracle's winner/qual/depth/errors, re-derives the masked
    columns and the (J, STATS_COLS) stats rows with exactly the device's
    integer math — consensus thresholds, the emin-table per-base compare,
    the min-base-quality compare — so any device bit flip in the fetched
    stats (or the survivor gather) shows as an exact mismatch. The
    suspect column is device-internal and stays 0 here; callers compare
    it separately. Returns (stats int32, masked_bases u8, masked_quals
    u8)."""
    w = np.asarray(winner, dtype=np.int32)
    q = np.asarray(qual, dtype=np.int32)
    d = np.asarray(depth, dtype=np.int32)
    e = np.asarray(errors, dtype=np.int32)
    n, L = w.shape
    lens = np.asarray(lens, dtype=np.int64)
    low_depth = d < np.int32(min_reads_c)
    low_qual = q < np.int32(min_qual_c)
    tb = np.where(low_depth | low_qual, N_CODE, w)
    tq = np.where(low_depth, 0, np.where(low_qual, MIN_PHRED, q))
    in_len = np.arange(L, dtype=np.int64)[None, :] < lens[:, None]
    d16 = np.minimum(d, _I16_MAX)
    e16 = np.minimum(e, _I16_MAX)
    per_base = bool(params.per_base)
    if per_base:
        fmask = (d16 < params.min_reads) \
            | ((d16 > 0) & (e16 >= params.emin_tab[d16]))
    else:
        fmask = np.zeros((n, L), dtype=bool)
    if int(params.min_base_q) >= 0:
        fmask = fmask | (tq < params.min_base_q)
    fmask = fmask & in_len
    fb = np.where(fmask, N_CODE, tb).astype(np.uint8)
    fq = np.where(fmask, MIN_PHRED, tq).astype(np.uint8)
    stats = np.zeros((n, STATS_COLS), dtype=np.int32)
    if L:
        stats[:, S_MAXD] = np.max(np.where(in_len, d16, 0), axis=1)
    stats[:, S_SUMD] = np.sum(np.where(in_len, d16, 0), axis=1,
                              dtype=np.int32)
    stats[:, S_SUME] = np.sum(np.where(in_len, e16, 0), axis=1,
                              dtype=np.int32)
    stats[:, S_QSUM] = np.sum(np.where(in_len, tq, 0), axis=1,
                              dtype=np.int32)
    stats[:, S_NAFTER] = np.sum(in_len & (fb == N_CODE), axis=1)
    stats[:, S_NEWLY] = np.sum(fmask & (tb != N_CODE), axis=1)
    return stats, fb, fq


class SimplexFilterStage:
    """Fused filter stage for the fast simplex engine (one per run).

    Thread-safe: resolve workers call :meth:`resolve_chunk` concurrently;
    only the stats accumulation is shared."""

    def __init__(self, config: FilterConfig, options,
                 filter_by_template: bool = True):
        from ..commands.filter import FilterStats

        self.config = config
        self.options = options  # VanillaOptions (consensus thresholds)
        self.filter_by_template = filter_by_template
        self.stats = FilterStats()
        self.dev_params = DeviceFilterParams(config,
                                             options.produce_per_base_tags)
        self._lock = threading.Lock()
        self._slow_tap = None

    # ---------------------------------------------------------- host twin

    def host_filter_columns(self, bases, quals, depth, errors, lens):
        """Host twin of the fused kernel's filter math over post-threshold
        (J, L) columns. Returns (masked_bases, masked_quals, stats) with
        ``stats`` shaped (J, STATS_COLS) — the same layout the device
        fetches, so the verdict code downstream is path-blind."""
        cfg = self.config
        n, L = bases.shape
        lens = np.asarray(lens, dtype=np.int64)
        in_len = np.arange(L)[None, :] < lens[:, None]
        d16 = np.minimum(depth, _I16_MAX).astype(np.int64)
        e16 = np.minimum(errors, _I16_MAX).astype(np.int64)
        if self.dev_params.per_base:
            mask = simplex_base_mask_arrays(d16, e16, quals, in_len,
                                            cfg.single_strand,
                                            cfg.min_base_quality)
        else:
            mask = np.zeros((n, L), dtype=bool)
            if cfg.min_base_quality is not None:
                mask = (quals < cfg.min_base_quality) & in_len
        fb = np.where(mask, N_CODE, bases).astype(np.uint8)
        fq = np.where(mask, MIN_PHRED, quals).astype(np.uint8)
        stats = np.zeros((n, STATS_COLS), dtype=np.int64)
        stats[:, S_MAXD] = np.max(np.where(in_len, d16, 0), axis=1) \
            if L else 0
        stats[:, S_SUMD] = np.sum(np.where(in_len, d16, 0), axis=1)
        stats[:, S_SUME] = np.sum(np.where(in_len, e16, 0), axis=1)
        stats[:, S_QSUM] = np.sum(
            np.where(in_len, quals.astype(np.int64), 0), axis=1)
        stats[:, S_NAFTER] = np.sum(in_len & (fb == N_CODE), axis=1)
        stats[:, S_NEWLY] = np.sum(mask & (bases != N_CODE), axis=1)
        return fb, fq, stats

    # ------------------------------------------------------------ verdicts

    def read_verdicts(self, stats, lens):
        """Per-read verdict codes from the stats rows (device or host).

        The cE tag value is float32(tot_e)/float32(tot_d) — exactly the
        native serializer's arithmetic — recomputed here from the exact
        integer sums, then judged by the shared array core."""
        sum_d = stats[:, S_SUMD]
        ce = np.zeros(len(sum_d), dtype=np.float32)
        nz = sum_d > 0
        ce[nz] = stats[nz, S_SUME].astype(np.float32) \
            / sum_d[nz].astype(np.float32)
        cfg = self.config
        return simplex_read_verdicts(
            stats[:, S_MAXD], ce, stats[:, S_QSUM], stats[:, S_NAFTER],
            lens, cfg.single_strand, cfg.min_mean_base_quality,
            cfg.max_no_call_fraction)

    def template_keep(self, verdicts, mi_rec):
        """Keep flags under --filter-by-template: consensus outputs are all
        primary, and jobs of one group (same ``mi_rec``) share a QNAME —
        the template passes iff every member passes."""
        ok = verdicts == R_PASS
        if not self.filter_by_template or not len(ok):
            return ok
        mi_rec = np.asarray(mi_rec)
        t_of = np.concatenate(([0], np.cumsum(mi_rec[1:] != mi_rec[:-1])))
        n_t = int(t_of[-1]) + 1
        t_fail = np.zeros(n_t, dtype=bool)
        np.logical_or.at(t_fail, t_of, ~ok)
        return ~t_fail[t_of]

    def _account(self, verdicts, keep, newly):
        with self._lock:
            st = self.stats
            st.total_records += len(verdicts)
            kept = int(keep.sum())
            st.passed_records += kept
            st.failed_records += len(verdicts) - kept
            st.bases_masked += int(np.asarray(newly)[keep].sum())
            for v in verdicts[~keep]:
                st.rejection_reasons[
                    RESULT_NAMES[int(v)] if v != R_PASS
                    else "template_failed"] += 1

    # ------------------------------------------------------------- resolve

    def resolve_chunk(self, chunk) -> bytes:
        """Fused resolve of one _PendingChunk: complete the device work,
        judge every job, and serialize only the survivors."""
        fast = chunk.fast
        caller = fast.caller
        kernel = caller.kernel
        table = chunk.jobs
        opts = caller.options
        J = len(table)
        blocks = []  # (idxs, fb, fq, d32, e32) — masked survivors' columns
        stats_all = np.zeros((J, STATS_COLS), dtype=np.int64)
        newly = np.zeros(J, dtype=np.int64)

        def add_full_columns(idxs, winner, qual, depth, errors):
            """Full post-oracle columns (host route / degraded device
            route / single-read blocks): thresholds + host filter math."""
            b, q = oracle.apply_consensus_thresholds(
                winner, qual, depth, opts.min_reads,
                opts.min_consensus_base_quality)
            fb, fq, stats = self.host_filter_columns(
                b, q, depth, errors, table.cons_len[idxs])
            stats_all[idxs] = stats
            newly[idxs] = stats[:, S_NEWLY]
            blocks.append((np.asarray(idxs, dtype=np.int64),
                           np.ascontiguousarray(fb),
                           np.ascontiguousarray(fq),
                           np.ascontiguousarray(depth, dtype=np.int32),
                           np.ascontiguousarray(errors, dtype=np.int32)))

        for idxs, b, q, d, e in chunk.blocks:
            # pre-threshold single-read host blocks arrive post-threshold
            # (single_read_consensus already masked); run only the filter
            fb, fq, stats = self.host_filter_columns(
                b, q, d, e, table.cons_len[idxs])
            stats_all[idxs] = stats
            newly[idxs] = stats[:, S_NEWLY]
            blocks.append((np.asarray(idxs, dtype=np.int64),
                           np.ascontiguousarray(fb),
                           np.ascontiguousarray(fq),
                           np.ascontiguousarray(d, dtype=np.int32),
                           np.ascontiguousarray(e, dtype=np.int32)))

        fused = None  # (multi idxs, resident, fused stats rows)
        pending = chunk.pending
        if pending is None:
            pass
        elif pending[0] == "seg":
            _, idxs, starts, codes_d, quals_d, dev = pending
            w, q, d, e = kernel.resolve_segments(dev, codes_d, quals_d,
                                                 starts)
            add_full_columns(idxs, w, q, d, e)
        elif pending[0] == "cols":
            _, idxs, pend = pending
            w, q, d, e = kernel.resolve_hard_columns(pend)
            add_full_columns(idxs, w, q, d, e)
        elif pending[0] == "segwf":
            _, idxs, starts, codes_d, quals_d, ticket = pending
            out = kernel.resolve_segments_wire_filtered(
                ticket, codes_d, quals_d, starts)
            if out[0] == "columns":
                add_full_columns(idxs, *out[1:])
            else:
                _, dev_stats, resident = out
                fused = self._fused_rows(kernel, table, idxs, starts,
                                         codes_d, quals_d, dev_stats,
                                         resident, stats_all, newly,
                                         add_full_columns)
        else:  # "segw": standard wire ticket (mesh route etc.)
            _, idxs, starts, codes_d, quals_d, ticket = pending
            w, q, d, e = kernel.resolve_segments_wire(
                ticket, codes_d, quals_d, starts)
            add_full_columns(idxs, w, q, d, e)

        verdicts = self.read_verdicts(stats_all, table.cons_len)
        keep = self.template_keep(verdicts, table.mi_rec)
        self._account(verdicts, keep, newly)

        if fused is not None:
            self._gather_fused(kernel, table, fused, keep, blocks,
                               add_full_columns)

        keep_idx = np.nonzero(keep)[0]
        caller.stats.add_consensus_reads(J - len(keep_idx))  # rejected jobs
        sub = _subset_table(table, keep_idx)
        remap = np.full(J, -1, dtype=np.int64)
        remap[keep_idx] = np.arange(len(keep_idx))
        kept_blocks = []
        for idxs, fb, fq, d32, e32 in blocks:
            sel = keep[idxs]
            if not sel.any():
                continue
            kept_blocks.append((remap[idxs[sel]], fb[sel], fq[sel],
                                np.ascontiguousarray(d32[sel]),
                                np.ascontiguousarray(e32[sel])))
        return fast._serialize_jobs(chunk.batch, sub, kept_blocks)

    def _fused_rows(self, kernel, table, idxs, starts, codes_d, quals_d,
                    dev_stats, resident, stats_all, newly,
                    add_full_columns):
        """Fold a fused stats fetch into the per-job arrays; suspect rows
        take the raw-column gather + ordinary host completion."""
        idxs = np.asarray(idxs, dtype=np.int64)
        k = len(idxs)
        st = dev_stats[:k].astype(np.int64)
        sus = st[:, S_SUSPECT] > 0
        clean = ~sus
        stats_all[idxs[clean]] = st[clean]
        newly[idxs[clean]] = st[clean, S_NEWLY]
        if sus.any():
            rows = np.nonzero(sus)[0]
            try:
                w, q, d, e = kernel.filter_resolve_suspect_rows(
                    resident, rows, starts, codes_d, quals_d)
            except BaseException as exc:  # noqa: BLE001 - weather-classified
                if not _is_device_weather(exc):
                    raise
                w, q, d, e = _host_rows(kernel, starts, codes_d, quals_d,
                                        rows)
            add_full_columns(idxs[rows], w, q, d, e)
        return (idxs, resident, clean, starts, codes_d, quals_d)

    def _gather_fused(self, kernel, table, fused, keep, blocks,
                      add_full_columns):
        """Stage-2 gather: fetch only surviving fused rows' masked columns
        (suspect rows already resolved host-side). Device weather on the
        gather degrades to the native f64 host engine for the kept rows —
        byte-identical, like every other degrade path."""
        idxs, resident, clean, starts, codes_d, quals_d = fused
        from ..ops.router import ROUTER

        try:
            want = clean & keep[idxs]
            rows = np.nonzero(want)[0]
            ROUTER.observe_filter_keep(len(rows), int(clean.sum()))
            if len(rows):
                try:
                    fb, fq, d32, e32 = kernel.filter_gather_filtered(
                        resident, rows)
                    blocks.append((idxs[rows], fb, fq, d32, e32))
                except BaseException as exc:  # noqa: BLE001 - classified
                    if not _is_device_weather(exc):
                        raise
                    w, q, d, e = _host_rows(kernel, starts, codes_d,
                                            quals_d, rows)
                    add_full_columns(idxs[rows], w, q, d, e)
        finally:
            resident.release()

    def filter_records_blob(self, blob: bytes) -> bytes:
        """Classic per-record filter over a slow-path blob (complete name
        groups only); stats fold into this stage's counters."""
        with self._lock:
            tap = self._slow_tap
            if tap is None:
                tap = self._slow_tap = HostFilterTap(
                    self.config, self.filter_by_template, stats=self.stats,
                    lock=self._lock)
        return tap.feed(blob) + tap.flush()


def _is_device_weather(exc) -> bool:
    """True for the recoverable device-failure classes (the same set every
    resolve path degrades on): deadline overrun, transient XLA error, OOM."""
    from ..ops.kernel import DeadlineExceeded, _is_oom, _is_transient

    return (isinstance(exc, DeadlineExceeded) or _is_oom(exc)
            or _is_transient(exc))


def _host_rows(kernel, starts, codes2d, quals2d, rows):
    """Native f64 host-engine completion of a subset of a dispatch's
    families (the fused route's gather-failure fallback): post-oracle
    (winner, qual, depth, errors) for ``rows``, byte-identical to the
    device path by the engines' shared exactness contract."""
    starts = np.asarray(starts, dtype=np.int64)
    counts = np.diff(starts)[rows]
    sub_starts = np.concatenate(([0], np.cumsum(counts)))
    sel = np.concatenate([np.arange(starts[r], starts[r + 1])
                          for r in rows])
    return kernel._host_engine_complete(codes2d[sel], quals2d[sel],
                                        sub_starts)


def _subset_table(table, keep_idx):
    """A _JobTable view of the kept jobs (pool arrays are shared — vlo and
    count keep indexing the original row pool)."""
    from .fast import _JobTable

    return _JobTable(table.count[keep_idx], table.vlo[keep_idx],
                     table.read_type[keep_idx], table.cons_len[keep_idx],
                     table.mi_rec[keep_idx], table.pool_rows,
                     table.pool_span)


class HostFilterTap:
    """In-process consensus-output filter over serialized record chunks.

    The fused route for outputs that are not (yet) column-resident: the
    simplex slow path's boundary groups and the duplex/codec engines. Each
    fed blob is a run of block_size-prefixed records; records are judged by
    the per-record reference filter (commands/filter.py::_process_one) with
    template grouping by QNAME, and only survivors are returned. Call
    :meth:`flush` after the last blob (the open name group is held back)."""

    def __init__(self, config: FilterConfig, filter_by_template: bool = True,
                 stats=None, lock=None):
        from ..commands.filter import FilterStats

        self.config = config
        self.filter_by_template = filter_by_template
        self.stats = stats if stats is not None else FilterStats()
        self._group = []       # [(record bytes)] of the open name group
        self._group_name = None
        self._lock = lock if lock is not None else threading.Lock()

    @staticmethod
    def _records(blob):
        off = 0
        view = memoryview(blob)
        while off < len(view):
            size = int.from_bytes(view[off:off + 4], "little")
            yield bytes(view[off + 4:off + 4 + size])
            off += 4 + size

    @staticmethod
    def _name(data: bytes) -> bytes:
        l_read_name = data[8]
        return bytes(data[32:32 + l_read_name - 1])

    def feed(self, blob: bytes) -> bytes:
        """Filter one serialized chunk; returns the kept wire bytes."""
        out = []
        with self._lock:
            for data in self._records(blob):
                name = self._name(data)
                if name != self._group_name and self._group:
                    out.append(self._emit_group_locked())
                self._group_name = name
                self._group.append(data)
        return b"".join(out)

    def flush(self) -> bytes:
        with self._lock:
            if not self._group:
                return b""
            return self._emit_group_locked()

    def _emit_group_locked(self) -> bytes:
        from ..commands.filter import _process_one
        from ..io.bam import (FLAG_SECONDARY, FLAG_SUPPLEMENTARY, RawRecord)
        from .filter import template_passes

        records = self._group
        self._group = []
        self._group_name = None
        processed = [_process_one(data, self.config, False, None, ())
                     for data in records]
        recs = [RawRecord(d) for d, _, _ in processed]
        results = [r for _, r, _ in processed]
        pass_flags = [r == PASS for r in results]
        tpl_pass = template_passes(recs, pass_flags) \
            if self.filter_by_template else True
        st = self.stats
        out = []
        for rec, okf, result, (_, _, mk) in zip(recs, pass_flags, results,
                                                processed):
            st.total_records += 1
            is_sec = bool(rec.flag & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY))
            if not self.filter_by_template:
                kp = okf
            elif is_sec:
                kp = tpl_pass and okf
            else:
                kp = tpl_pass
            if kp:
                st.passed_records += 1
                st.bases_masked += 0 if is_sec else mk
                out.append(len(rec.data).to_bytes(4, "little") + rec.data)
            else:
                st.failed_records += 1
                st.rejection_reasons[
                    result if result != PASS else "template_failed"] += 1
        return b"".join(out)


def make_filter_tap(args):
    """HostFilterTap for a consensus command's ``--device-filter`` request,
    or None when not requested. Raises ValueError on bad thresholds (the
    CLI reports it and exits 2). One constructor for the duplex/codec/
    classic-simplex wiring sites."""
    if not device_filter_requested(args):
        return None
    return HostFilterTap(filter_config_from_args(args),
                         args.filter_by_template)


def wrap_filter_writer(writer, tap):
    """``writer`` unchanged when ``tap`` is None, else the tap-filtering
    wrapper (callers still call ``.finish()`` after the last write)."""
    return writer if tap is None else FilterTapWriter(writer, tap)


class FilterTapWriter:
    """Writer wrapper routing every serialized chunk through a
    :class:`HostFilterTap` (the duplex/codec ``--device-filter`` route)."""

    def __init__(self, writer, tap: HostFilterTap):
        self._writer = writer
        self.tap = tap

    def write_serialized(self, blob):
        kept = self.tap.feed(bytes(blob))
        if kept:
            self._writer.write_serialized(kept)

    def write_record_bytes(self, rec):
        kept = self.tap.feed(len(rec).to_bytes(4, "little") + bytes(rec))
        if kept:
            self._writer.write_serialized(kept)

    def finish(self):
        kept = self.tap.flush()
        if kept:
            self._writer.write_serialized(kept)

    def __getattr__(self, name):
        return getattr(self._writer, name)
