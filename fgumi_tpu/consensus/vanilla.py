"""Vanilla (simplex) UMI consensus caller.

Host-side orchestration mirroring the reference pipeline
(/root/reference/crates/fgumi-consensus/src/vanilla_caller.rs:1119-1331: filter
secondary/supplementary -> min_reads -> downsample -> subgroup fragment/R1/R2 ->
SourceRead conversion (RC, quality mask, mate-overlap trim, trailing-N trim) ->
most-common-alignment filter -> consensus -> raw BAM record with cD/cM/cE/cd/ce/MI),
with the per-position likelihood loop replaced by the batched TPU kernel
(fgumi_tpu.ops.kernel) over padded (family, read, position) tensors.

Determinism contract: downsampling uses a NumPy Philox generator seeded per group
from (seed, group ordinal); the reference documents its own selection as
deterministic-per-seed but not byte-identical to fgbio (vanilla_caller.rs:829-835) —
this build makes the same promise with its own pinned stream.
"""

import threading
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..constants import (BASE_TO_CODE, CODE_TO_BASE, MAX_PHRED, MIN_PHRED,
                         N_CODE, reverse_complement_codes)
from ..core import cigar as cigar_utils
from ..core.overlap import num_bases_extending_past_mate
from ..io.bam import (FLAG_FIRST, FLAG_LAST, FLAG_MATE_UNMAPPED, FLAG_PAIRED,
                      FLAG_REVERSE, FLAG_SECONDARY, FLAG_SUPPLEMENTARY,
                      FLAG_UNMAPPED, RawRecord, RecordBuilder)
from ..ops import oracle
from ..ops.kernel import ConsensusKernel
from ..ops.tables import quality_tables
from .rejects import RejectTracking
from .simple_umi import consensus_umis

I16_MAX = 32767

# Read types (order matters for output: fragment, then R1, then R2).
FRAGMENT, R1, R2 = 0, 1, 2
_TYPE_FLAGS = {
    FRAGMENT: FLAG_UNMAPPED,
    R1: FLAG_UNMAPPED | FLAG_PAIRED | FLAG_FIRST | FLAG_MATE_UNMAPPED,
    R2: FLAG_UNMAPPED | FLAG_PAIRED | FLAG_LAST | FLAG_MATE_UNMAPPED,
}


@dataclass
class VanillaOptions:
    """Mirrors VanillaUmiConsensusOptions defaults (vanilla_caller.rs:327-344)."""

    tag: str = "MI"
    error_rate_pre_umi: int = 45
    error_rate_post_umi: int = 40
    min_input_base_quality: int = 10
    min_reads: int = 2
    max_reads: Optional[int] = None
    produce_per_base_tags: bool = True
    seed: Optional[int] = 42
    trim: bool = False
    min_consensus_base_quality: int = 40
    # None | "em-seq" | "taps" (methylation.rs MethylationMode)
    methylation_mode: Optional[str] = None


@dataclass
class CallerStats:
    """Aggregate statistics (ConsensusCallingStats analog).

    `add_consensus_reads` takes the lock because that counter is bumped from
    whichever thread resolves a deferred batch (the pipeline's writer stage)
    while input_reads/rejected stay on the processing thread.
    """

    input_reads: int = 0
    consensus_reads: int = 0
    rejected: dict = field(default_factory=dict)
    lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def add_consensus_reads(self, count: int):
        with self.lock:
            self.consensus_reads += count

    def reject(self, reason: str, count: int):
        self.rejected[reason] = self.rejected.get(reason, 0) + count

    def merge(self, other: "CallerStats"):
        self.input_reads += other.input_reads
        self.consensus_reads += other.consensus_reads
        for k, v in other.rejected.items():
            self.reject(k, v)


@dataclass
class SourceRead:
    """Transformed read (vanilla_caller.rs:125-150): oriented, masked, trimmed."""

    original_idx: int
    codes: np.ndarray  # uint8 base codes 0..4
    quals: np.ndarray  # uint8
    simplified_cigar: list
    flags: int
    ref_id: int = -1
    alignment_start: int = -1  # 0-based
    original_cigar: list = None  # simplified, un-reversed (methylation anchor)


@dataclass
class ConsensusJob:
    """One subgroup's device work unit."""

    umi: str
    read_type: int
    codes: list  # list of per-read code arrays (variable length)
    quals: list
    consensus_len: int
    original_raws: list  # RawRecords surviving filtering (for tag extraction)
    source_reads: list = None  # SourceReads (kept when the caller needs them, e.g. duplex)
    methylation: object = None  # (MethylationAnnotation, is_top) when enabled


@dataclass
class VanillaConsensusRead:
    """Intermediate single-strand consensus (VanillaConsensusRead, vanilla_caller.rs:153-180)."""

    id: str
    bases: np.ndarray  # uint8 codes 0..4
    quals: np.ndarray  # uint8
    depths: np.ndarray  # int64, already clamped to I16_MAX per base
    errors: np.ndarray  # int64, already clamped to I16_MAX per base
    source_reads: list = None
    methylation: object = None  # (MethylationAnnotation, is_top) when enabled

    def max_depth(self) -> int:
        return int(self.depths.max()) if len(self.depths) else 0


def find_quality_trim_point(quals: np.ndarray, trim_qual: int) -> int:
    """htsjdk TrimmingUtil.findQualityTrimPoint (vanilla_caller.rs:857-881)."""
    length = len(quals)
    if trim_qual < 1 or length == 0:
        return 0
    score = 0
    max_score = 0
    trim_point = length
    for i in range(length - 1, -1, -1):
        score += trim_qual - int(quals[i])
        if score < 0:
            break
        if score > max_score:
            max_score = score
            trim_point = i
    return trim_point


class VanillaConsensusCaller(RejectTracking):
    """Simplex consensus caller over MI groups, batched onto the TPU kernel."""

    def __init__(self, read_name_prefix: str, read_group_id: str,
                 options: VanillaOptions = None, kernel: ConsensusKernel = None,
                 reference=None, ref_names=None, track_rejects: bool = False):
        """`reference`: chrom -> bytes mapping (or any .get-able) and
        `ref_names`: BAM ref_id -> name list; both required only for
        methylation-aware calling. With `track_rejects`, raw records that do
        not contribute to any consensus accumulate in `rejected_reads` (the
        reference's secondary rejects stream, base.rs:1838)."""
        self.options = options or VanillaOptions()
        self.reference = reference
        self.ref_names = ref_names or []
        self.prefix = read_name_prefix
        self.read_group_id = read_group_id
        self.tables = quality_tables(self.options.error_rate_pre_umi,
                                     self.options.error_rate_post_umi)
        self.kernel = kernel or ConsensusKernel(self.tables)
        self.stats = CallerStats()
        self._init_rejects(track_rejects)
        self._builder = RecordBuilder()
        self._group_ordinal = 0

    # ------------------------------------------------------------------ host prep

    def _create_source_read(self, rec: RawRecord, idx: int, mate_clip: int):
        """SourceRead conversion (create_source_read, vanilla_caller.rs:940-1032)."""
        opts = self.options
        quals = rec.quals()
        read_len = rec.l_seq
        if read_len == 0 or len(quals) != read_len:
            return None
        # BAM spec: absent quals are 0xFF-filled; reject (vanilla_caller.rs:962-967)
        if (quals == 0xFF).all():
            return None
        codes = BASE_TO_CODE[np.frombuffer(rec.seq_bytes(), dtype=np.uint8)]

        is_negative = bool(rec.flag & FLAG_REVERSE)
        if is_negative:
            codes = reverse_complement_codes(codes)
            quals = quals[::-1].copy()
        else:
            codes = codes.copy()

        trim_to = find_quality_trim_point(quals, opts.min_input_base_quality) \
            if opts.trim else read_len

        # mask low-quality bases to N/Q2 up to the trim point
        mask = quals[:trim_to] < opts.min_input_base_quality
        codes[:trim_to][mask] = N_CODE
        quals[:trim_to][mask] = MIN_PHRED

        final_len = min(max(read_len - mate_clip, 0), trim_to)
        while final_len > 0 and codes[final_len - 1] == N_CODE:
            final_len -= 1
        if final_len == 0:
            return None

        original_simplified = cigar_utils.simplify(rec.cigar())
        simplified = original_simplified
        if is_negative:
            simplified = cigar_utils.reverse(simplified)
        simplified = cigar_utils.truncate_to_query_length(simplified, final_len)

        return SourceRead(original_idx=idx, codes=codes[:final_len],
                          quals=quals[:final_len], simplified_cigar=simplified,
                          flags=rec.flag, ref_id=rec.ref_id,
                          alignment_start=rec.pos,
                          original_cigar=original_simplified)

    def _filter_by_alignment(self, source_reads):
        """Most-common-alignment filter (vanilla_caller.rs:1038-1089)."""
        if len(source_reads) < 2:
            return source_reads
        indexed = sorted(
            ((i, len(sr.codes), sr.simplified_cigar) for i, sr in enumerate(source_reads)),
            key=lambda t: -t[1],
        )
        keep = set(cigar_utils.select_most_common_alignment_group(indexed))
        rejected = len(source_reads) - len(keep)
        if rejected:
            self.stats.reject("MinorityAlignment", rejected)
        return [sr for i, sr in enumerate(source_reads) if i in keep]

    def _annotate_methylation(self, source_reads):
        """EM-Seq/TAPS annotate + normalize (vanilla_caller.rs
        annotate_and_normalize): maps the longest read's query positions to the
        reference, counts conversion evidence at ref-C positions, and rewrites
        converted bases so scoring treats conversion as agreement.

        Returns (annotation, is_top) or None when disabled/unmappable.
        """
        if not self.options.methylation_mode or self.reference is None:
            return None
        if not source_reads:
            return None
        from . import methylation

        anchor = max(source_reads, key=lambda sr: len(sr.codes))
        if anchor.ref_id < 0 or anchor.alignment_start < 0 \
                or anchor.ref_id >= len(self.ref_names):
            return None
        ref_name = self.ref_names[anchor.ref_id]
        ref_seq = self.reference.get(ref_name) \
            if hasattr(self.reference, "get") else None
        if ref_seq is None:
            # warn once: a BAM/FASTA contig-name mismatch (chr1 vs 1) would
            # otherwise silently disable methylation for the whole run
            if not getattr(self, "_warned_missing_contig", False):
                self._warned_missing_contig = True
                import logging

                logging.getLogger("fgumi_tpu").warning(
                    "contig %r not found in the reference FASTA; methylation "
                    "annotation is skipped for reads on missing contigs",
                    ref_name)
            return None
        is_top = methylation.is_top_strand(anchor.flags)
        ref_positions = methylation.query_to_ref_positions(
            anchor.simplified_cigar, anchor.alignment_start,
            bool(anchor.flags & FLAG_REVERSE), anchor.original_cigar or [])
        ref_codes = methylation.ref_codes_at_positions(ref_positions, ref_seq)
        annotation = methylation.annotate(source_reads, ref_codes, is_top)
        methylation.normalize_source_reads(source_reads, annotation, is_top)
        return annotation, is_top

    def _downsample(self, items: list, rng) -> list:
        """Seeded shuffle-take-max_reads (vanilla_caller.rs:799-845)."""
        max_reads = self.options.max_reads
        if max_reads is None or len(items) <= max_reads:
            return items
        perm = rng.permutation(len(items))[:max_reads]
        return [items[i] for i in perm]

    def prepare_group(self, umi: str, records: list):
        """Host prep for one MI group -> list of ConsensusJob (process_group)."""
        self.stats.input_reads += len(records)
        opts = self.options
        ordinal = self._group_ordinal
        self._group_ordinal += 1

        reads = [r for r in records
                 if not r.flag & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY)]
        if len(reads) < len(records):
            self.stats.reject("SecondaryOrSupplementary", len(records) - len(reads))
            self._reject_records(
                r for r in records
                if r.flag & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY))
        if not reads:
            return []
        if len(reads) < opts.min_reads:
            self.stats.reject("InsufficientReads", len(reads))
            self._reject_records(reads)
            return []

        if opts.max_reads is not None and len(reads) > opts.max_reads:
            rng = np.random.Generator(np.random.Philox(key=(opts.seed or 0) + ordinal))
            reads = self._downsample(reads, rng)

        # subgroup by read type (vanilla_caller.rs:1096-1116)
        subgroups = {FRAGMENT: [], R1: [], R2: []}
        for r in reads:
            flg = r.flag
            if not flg & FLAG_PAIRED:
                subgroups[FRAGMENT].append(r)
            elif flg & FLAG_FIRST:
                subgroups[R1].append(r)
            elif flg & FLAG_LAST:
                subgroups[R2].append(r)

        jobs = {}
        for read_type in (FRAGMENT, R1, R2):
            group_reads = subgroups[read_type]
            if not group_reads:
                continue
            if len(group_reads) < opts.min_reads:
                self.stats.reject("InsufficientReads", len(group_reads))
                self._reject_records(group_reads)
                continue
            source_reads = []
            zero_len = 0
            for idx, rec in enumerate(group_reads):
                clip = num_bases_extending_past_mate(rec)
                sr = self._create_source_read(rec, idx, clip)
                if sr is None:
                    zero_len += 1
                    self._reject_records([rec])
                else:
                    source_reads.append(sr)
            if zero_len:
                self.stats.reject("ZeroLengthAfterTrimming", zero_len)
            if len(source_reads) < opts.min_reads:
                if source_reads:
                    self.stats.reject("InsufficientReads", len(source_reads))
                    self._reject_records(
                        group_reads[sr.original_idx] for sr in source_reads)
                continue
            before = source_reads
            source_reads = self._filter_by_alignment(source_reads)
            if len(source_reads) < len(before):
                kept_idx = {sr.original_idx for sr in source_reads}
                self._reject_records(group_reads[sr.original_idx]
                                     for sr in before
                                     if sr.original_idx not in kept_idx)
            if len(source_reads) < opts.min_reads:
                if source_reads:
                    self.stats.reject("InsufficientReads", len(source_reads))
                    self._reject_records(
                        group_reads[sr.original_idx] for sr in source_reads)
                continue
            meth = self._annotate_methylation(source_reads)
            lengths = sorted((len(sr.codes) for sr in source_reads), reverse=True)
            consensus_len = lengths[opts.min_reads - 1]
            jobs[read_type] = ConsensusJob(
                umi=umi, read_type=read_type,
                codes=[sr.codes for sr in source_reads],
                quals=[sr.quals for sr in source_reads],
                consensus_len=consensus_len,
                original_raws=[group_reads[sr.original_idx] for sr in source_reads],
                methylation=meth,
            )

        # orphan R1/R2 handling (vanilla_caller.rs:1166-1185): both or neither
        out = []
        if FRAGMENT in jobs:
            out.append(jobs[FRAGMENT])
        r1, r2 = jobs.get(R1), jobs.get(R2)
        if r1 is not None and r2 is not None:
            out.extend([r1, r2])
        elif r1 is not None:
            self.stats.reject("OrphanConsensus", len(r1.codes))
            self._reject_records(r1.original_raws)
        elif r2 is not None:
            self.stats.reject("OrphanConsensus", len(r2.codes))
            self._reject_records(r2.original_raws)
        return out

    def job_from_source_reads(self, umi: str, read_type: int, source_reads,
                              ordinal: int = 0, keep_source_reads: bool = False):
        """consensus_call analog (vanilla_caller.rs:635-706): build a ConsensusJob
        from pre-filtered SourceReads. The max_reads cap shapes only the consensus
        scoring set; the full set is retained on the job when requested (fgbio passes
        the pre-cap reads to duplexConsensus)."""
        opts = self.options
        if not source_reads or len(source_reads) < opts.min_reads:
            return None
        capped = source_reads
        if opts.max_reads is not None and len(source_reads) > opts.max_reads:
            rng = np.random.Generator(np.random.Philox(key=(opts.seed or 0) + ordinal))
            capped = self._downsample(source_reads, rng)
        if len(capped) < opts.min_reads:
            return None
        # methylation annotate + normalize on the scoring set (the duplex
        # SS stage's analog of prepare_group's annotation; duplex_caller.rs
        # routes methylation through ss_caller.options)
        meth = self._annotate_methylation(capped)
        lengths = sorted((len(sr.codes) for sr in capped), reverse=True)
        consensus_len = lengths[opts.min_reads - 1]
        return ConsensusJob(
            umi=umi, read_type=read_type,
            codes=[sr.codes for sr in capped], quals=[sr.quals for sr in capped],
            consensus_len=consensus_len, original_raws=[],
            source_reads=source_reads if keep_source_reads else None,
            methylation=meth)

    def result_to_consensus_read(self, job: ConsensusJob, result) -> VanillaConsensusRead:
        """Wrap a job's (already thresholded) _run_jobs outputs as a
        VanillaConsensusRead; per-base depths/errors clamp to fgbio's Short ceiling
        (vanilla_caller.rs:1414-1424)."""
        bases, quals, depth, errors = result
        return VanillaConsensusRead(
            id=job.umi, bases=np.asarray(bases), quals=np.asarray(quals),
            depths=np.minimum(depth, I16_MAX), errors=np.minimum(errors, I16_MAX),
            source_reads=job.source_reads, methylation=job.methylation)

    # ------------------------------------------------------------------ device

    def _run_jobs(self, jobs):
        """Execute jobs: single-read on host, multi-read via ONE ragged
        segment-sum dispatch (kernel.device_call_segments) per call.

        One device execution per job batch regardless of family-size mix —
        the same dense layout the fast simplex engine uses (consensus/fast.py
        _dispatch_jobs), so duplex/CODEC/classic callers share its economics.
        Returns per-job (bases_codes, quals, depths, errors) pre-threshold
        clamped arrays trimmed to consensus_len.
        """
        results = [None] * len(jobs)
        multi = []
        for j, job in enumerate(jobs):
            if len(job.codes) == 1:
                b, q, d, e = oracle.single_read_consensus(
                    job.codes[0][: job.consensus_len],
                    job.quals[0][: job.consensus_len],
                    self.tables, self.options.min_consensus_base_quality)
                results[j] = (b, q, d, e)
            else:
                multi.append(j)
        if not multi:
            return results

        L_max = -(-max(jobs[j].consensus_len for j in multi) // 16) * 16
        counts = np.array([len(jobs[j].codes) for j in multi], dtype=np.int64)
        N = int(counts.sum())

        if N <= 64:
            # Tiny workload (typically a batch-boundary carry group): call the
            # f64 oracle on host. The device result is defined as oracle-
            # integer-exact (guard band + suspect patch), so this is the same
            # bytes — without a micro dispatch that would serialize behind the
            # in-flight big batch on the device queue (round-4 profile: 0.6s
            # of queue wait per boundary group, ~10% of simplex wall).
            for j in multi:
                job = jobs[j]
                L = job.consensus_len
                R = len(job.codes)
                codes = np.full((R, L), N_CODE, dtype=np.uint8)
                quals = np.zeros((R, L), dtype=np.uint8)
                for r, (c, q) in enumerate(zip(job.codes, job.quals)):
                    n = min(len(c), L)
                    codes[r, :n] = c[:n]
                    quals[r, :n] = q[:n]
                w, q_, d, e = oracle.call_family(codes, quals, self.tables)
                b_j, q_j = oracle.apply_consensus_thresholds(
                    w, q_, d, self.options.min_reads,
                    self.options.min_consensus_base_quality)
                results[j] = (b_j, q_j, d, e)
            return results
        codes2d = np.full((N, L_max), N_CODE, dtype=np.uint8)
        quals2d = np.zeros((N, L_max), dtype=np.uint8)
        row = 0
        for j in multi:
            job = jobs[j]
            for c, q in zip(job.codes, job.quals):
                n = min(len(c), L_max)
                codes2d[row, :n] = c[:n]
                quals2d[row, :n] = q[:n]
                row += 1
        # same adaptive routing as the fast engines (ops/router.py) —
        # classic/--classic runs share their link economics
        from ..ops.kernel import route_and_call_segments

        starts = np.concatenate(([0], np.cumsum(counts)))
        w, q_, d, e = route_and_call_segments(self.kernel, codes2d, quals2d,
                                              counts, starts)
        for fi, j in enumerate(multi):
            L = jobs[j].consensus_len
            b_j, q_j = oracle.apply_consensus_thresholds(
                w[fi, :L], q_[fi, :L], d[fi, :L],
                self.options.min_reads, self.options.min_consensus_base_quality)
            results[j] = (b_j, q_j, d[fi, :L], e[fi, :L])
        return results

    # ------------------------------------------------------------------ output

    def _build_record(self, job: ConsensusJob, bases_codes, quals, depths, errors) -> bytes:
        """Serialize a consensus record (build_consensus_record_into,
        vanilla_caller.rs:1452-1540). Per-base depths/errors clamp to i16::MAX
        (fgbio Short semantics, vanilla_caller.rs:1414-1424)."""
        depths16 = np.minimum(depths, I16_MAX).astype(np.int32)
        errors16 = np.minimum(errors, I16_MAX).astype(np.int32)
        name = f"{self.prefix}:{job.umi}".encode()
        seq = CODE_TO_BASE[np.minimum(bases_codes, N_CODE)].tobytes()
        b = self._builder
        b.start_unmapped(name, _TYPE_FLAGS[job.read_type], seq, quals)
        b.tag_str(b"RG", self.read_group_id.encode())
        b.tag_int(b"cD", int(depths16.max()) if len(depths16) else 0)
        b.tag_int(b"cM", int(depths16.min()) if len(depths16) else 0)
        total_depth = int(depths16.sum())
        total_errors = int(errors16.sum())
        rate = np.float32(total_errors) / np.float32(total_depth) if total_depth else np.float32(0)
        b.tag_float(b"cE", float(rate))
        if self.options.produce_per_base_tags:
            b.tag_array_i16(b"cd", depths16)
            b.tag_array_i16(b"ce", errors16)
        b.tag_str(b"MI", job.umi.encode())
        # consensus RX from the surviving input reads' RX tags (vanilla_caller.rs:1522-1536)
        rx_umis = [u for u in (rec.get_str(b"RX") for rec in job.original_raws)
                   if u is not None]
        if rx_umis:
            b.tag_str(b"RX", consensus_umis(rx_umis).encode())
        # methylation tags (EM-Seq/TAPS; vanilla_caller.rs:1538-1560)
        if job.methylation is not None:
            from . import methylation as meth_mod

            annotation, anchor_is_top = job.methylation
            annotation = annotation.truncate(len(bases_codes))
            is_top = anchor_is_top
            if job.original_raws:
                is_top = meth_mod.is_top_strand(job.original_raws[0].flag)
            got = meth_mod.build_mm_ml(np.asarray(bases_codes), annotation,
                                       is_top, self.options.methylation_mode)
            if got is not None:
                mm, ml = got
                b.tag_str(b"MM", mm.encode())
                b.tag_array_u8(b"ML", np.frombuffer(ml, dtype=np.uint8))
            b.tag_array_i16(b"cu", annotation.cu())
            b.tag_array_i16(b"ct", annotation.ct())
        self.stats.add_consensus_reads(1)
        return b.finish()

    def call_groups(self, groups) -> list:
        """Process [(umi, [RawRecord])] -> list of consensus record bytes.

        Output order: group order, fragment/R1/R2 within a group (process_group).
        """
        jobs = []
        for umi, records in groups:
            jobs.extend(self.prepare_group(umi, records))
        if not jobs:
            return []
        results = self._run_jobs(jobs)
        return [self._build_record(job, *res) for job, res in zip(jobs, results)]
