"""Overlapping-pair base pre-correction before UMI consensus.

Port of the semantics of /root/reference/crates/fgumi-consensus/src/overlapping.rs:
R1/R2 of a template that overlap in their insert sequence the same molecule
positions; those bases are consensus-corrected *in place* before UMI consensus
so they are not double-counted (overlapping.rs:1-6).

- Aligned positions only (M/=/X), paired by shared reference position via a
  merge walk (ReadMateAndRefPosIterator, overlapping.rs:560-620) — here a
  vectorized intersect over each read's aligned (ref_pos, read_offset) arrays.
- No-call bases (N/n/.) are skipped entirely (overlapping.rs:13-18, 287-289).
- Agreement strategies (overlapping.rs:20-28): consensus (sum quals, cap Q93),
  max-qual, pass-through.
- Disagreement strategies (overlapping.rs:30-39): consensus (higher-quality
  base wins with the quality difference; equal quality masks both to N/Q2),
  mask-both, mask-lower-qual (tie masks both).
- apply_overlapping_consensus pairs primary R1/R2 records by name within a
  group (overlapping.rs:625-676).
"""

from dataclasses import dataclass

import numpy as np

from ..constants import MIN_PHRED, NO_CALL_BASE, NO_CALL_BASE_LOWER
from ..io.bam import (FLAG_FIRST, FLAG_LAST, FLAG_SECONDARY,
                      FLAG_SUPPLEMENTARY, FLAG_UNMAPPED, RawRecord, pack_seq)

AGREEMENT_STRATEGIES = ("consensus", "max-qual", "pass-through")
DISAGREEMENT_STRATEGIES = ("consensus", "mask-both", "mask-lower-qual")


@dataclass
class CorrectionStats:
    """CorrectionStats analog (overlapping.rs:41-77)."""

    overlapping_bases: int = 0
    bases_agreeing: int = 0
    bases_disagreeing: int = 0
    bases_corrected: int = 0

    def merge(self, other: "CorrectionStats"):
        self.overlapping_bases += other.overlapping_bases
        self.bases_agreeing += other.bases_agreeing
        self.bases_disagreeing += other.bases_disagreeing
        self.bases_corrected += other.bases_corrected


def aligned_positions(rec: RawRecord):
    """(ref_pos 1-based, read_offset 0-based) arrays for M/=/X positions."""
    refs = []
    offs = []
    ref_pos = rec.pos + 1
    read_off = 0
    for op, n in rec.cigar():
        if op in "M=X":
            refs.append(np.arange(ref_pos, ref_pos + n, dtype=np.int64))
            offs.append(np.arange(read_off, read_off + n, dtype=np.int64))
            ref_pos += n
            read_off += n
        elif op in "IS":
            read_off += n
        elif op in "DN":
            ref_pos += n
    if not refs:
        return (np.empty(0, np.int64), np.empty(0, np.int64))
    return np.concatenate(refs), np.concatenate(offs)


def _write_back(rec: RawRecord, seq: np.ndarray, quals: np.ndarray) -> RawRecord:
    """New record bytes with sequence (ASCII array) and qualities replaced."""
    buf = bytearray(rec.data)
    packed = pack_seq(seq)
    s_off = rec._seq_off()
    buf[s_off : s_off + len(packed)] = packed
    q_off = rec._qual_off()
    buf[q_off : q_off + len(quals)] = np.asarray(quals, np.uint8).tobytes()
    return RawRecord(bytes(buf))


class OverlappingBasesConsensusCaller:
    """In-place overlap corrector for one R1/R2 pair (overlapping.rs:80-345)."""

    def __init__(self, agreement: str = "consensus",
                 disagreement: str = "consensus"):
        if agreement not in AGREEMENT_STRATEGIES:
            raise ValueError(f"unknown agreement strategy {agreement!r}")
        if disagreement not in DISAGREEMENT_STRATEGIES:
            raise ValueError(f"unknown disagreement strategy {disagreement!r}")
        self.agreement = agreement
        self.disagreement = disagreement
        self.stats = CorrectionStats()

    def call(self, r1: RawRecord, r2: RawRecord):
        """Returns (r1', r2', processed): corrected records (or the originals)
        and whether the pair overlapped at all."""
        if (r1.flag | r2.flag) & FLAG_UNMAPPED or r1.ref_id != r2.ref_id:
            return r1, r2, False
        if r1.reference_length() == 0 or r2.reference_length() == 0:
            return r1, r2, False

        ref1, off1 = aligned_positions(r1)
        ref2, off2 = aligned_positions(r2)
        _, i1, i2 = np.intersect1d(ref1, ref2, assume_unique=True,
                                   return_indices=True)
        if len(i1) == 0:
            return r1, r2, False
        o1, o2 = off1[i1], off2[i2]

        seq1 = np.frombuffer(r1.seq_bytes(), dtype=np.uint8).copy()
        seq2 = np.frombuffer(r2.seq_bytes(), dtype=np.uint8).copy()
        q1 = r1.quals().copy()
        q2 = r2.quals().copy()

        b1, b2 = seq1[o1], seq2[o2]
        no_call = np.isin(b1, (NO_CALL_BASE, NO_CALL_BASE_LOWER, ord("."))) | \
            np.isin(b2, (NO_CALL_BASE, NO_CALL_BASE_LOWER, ord(".")))
        keep = ~no_call
        o1, o2, b1, b2 = o1[keep], o2[keep], b1[keep], b2[keep]
        if len(o1) == 0:
            return r1, r2, True
        qa = q1[o1].astype(np.int32)
        qb = q2[o2].astype(np.int32)

        agree = b1 == b2
        n_agree = int(agree.sum())
        n_disagree = len(b1) - n_agree
        self.stats.overlapping_bases += len(b1)
        self.stats.bases_agreeing += n_agree
        self.stats.bases_disagreeing += n_disagree
        modified = False

        if n_agree and self.agreement != "pass-through":
            ai1, ai2 = o1[agree], o2[agree]
            if self.agreement == "consensus":
                new_q = np.minimum(qa[agree] + qb[agree], 93)
            else:  # max-qual
                new_q = np.maximum(qa[agree], qb[agree])
            changed = (new_q != qa[agree]) | (new_q != qb[agree])
            self.stats.bases_corrected += int(changed.sum())
            if changed.any():
                modified = True
            q1[ai1] = new_q
            q2[ai2] = new_q

        if n_disagree:
            modified = True
            d = ~agree
            di1, di2 = o1[d], o2[d]
            da, db = qa[d], qb[d]
            ba_, bb_ = b1[d], b2[d]
            if self.disagreement == "consensus":
                # higher quality wins with the difference; tie -> N/Q2 both
                win_a = da > db
                win_b = db > da
                tie = da == db
                new_base = np.where(tie, NO_CALL_BASE, np.where(win_a, ba_, bb_))
                new_q = np.where(
                    tie, MIN_PHRED,
                    np.maximum(np.abs(da - db), MIN_PHRED))
                seq1[di1] = new_base
                seq2[di2] = new_base
                q1[di1] = new_q
                q2[di2] = new_q
                self.stats.bases_corrected += 2 * n_disagree
            elif self.disagreement == "mask-both":
                seq1[di1] = NO_CALL_BASE
                seq2[di2] = NO_CALL_BASE
                q1[di1] = MIN_PHRED
                q2[di2] = MIN_PHRED
                self.stats.bases_corrected += 2 * n_disagree
            else:  # mask-lower-qual: lower masked; tie masks both
                mask1 = da <= db
                mask2 = db <= da
                seq1[di1[mask1]] = NO_CALL_BASE
                q1[di1[mask1]] = MIN_PHRED
                seq2[di2[mask2]] = NO_CALL_BASE
                q2[di2[mask2]] = MIN_PHRED
                self.stats.bases_corrected += int(mask1.sum()) + int(mask2.sum())

        if not modified:
            return r1, r2, True
        return _write_back(r1, seq1, q1), _write_back(r2, seq2, q2), True


def apply_overlapping_consensus(records: list,
                                caller: OverlappingBasesConsensusCaller) -> list:
    """Correct every primary R1/R2 pair (matched by name) within a group.

    Returns the records list with corrected pairs replaced in position
    (apply_overlapping_consensus, overlapping.rs:625-676). When the native
    runtime is available, all pairs of the group run in one C call over a
    concatenated buffer (the same fgumi_overlap_correct_pairs the fast
    simplex engine uses); the per-pair numpy path is the fallback and the
    semantic reference (tests/test_overlapping.py parity test).
    """
    pairs = {}
    for idx, rec in enumerate(records):
        flg = rec.flag
        if flg & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY):
            continue
        slot = pairs.setdefault(rec.name, [None, None])
        if flg & FLAG_FIRST:
            slot[0] = idx
        elif flg & FLAG_LAST:
            slot[1] = idx
    complete = [(i1, i2) for i1, i2 in pairs.values()
                if i1 is not None and i2 is not None]
    if not complete:
        return list(records)

    from ..native import batch as nb

    if nb.available():
        return _apply_native(records, complete, caller)
    return apply_overlapping_consensus_python(records, complete, caller)


def apply_overlapping_consensus_python(records, complete, caller):
    """The per-pair pure-Python correction (the native path's semantic
    reference; forced directly by the parity tests)."""
    out = list(records)
    for i1, i2 in complete:
        r1, r2, _ = caller.call(out[i1], out[i2])
        out[i1], out[i2] = r1, r2
    return out


def add_native_overlap_stats(stats_obj, stats_arr):
    """Fold a fgumi_overlap_correct_pairs stats array into CorrectionStats
    (shared by this module and the fast simplex engine)."""
    stats_obj.overlapping_bases += int(stats_arr[0])
    stats_obj.bases_agreeing += int(stats_arr[1])
    stats_obj.bases_disagreeing += int(stats_arr[2])
    stats_obj.bases_corrected += int(stats_arr[3])


def _apply_native(records, complete, caller):
    """One fgumi_overlap_correct_pairs call over the paired records only."""
    from ..native import batch as nb

    # concatenate just the touched records; untouched ones pass through
    touched = sorted({i for pair in complete for i in pair})
    offsets = {}
    off = 0
    parts = []
    for i in touched:
        parts.append(records[i].data)
        offsets[i] = off
        off += len(records[i].data)
    buf = np.frombuffer(bytearray(b"".join(parts)), dtype=np.uint8)
    r1_offs = np.array([offsets[i1] for i1, _ in complete], dtype=np.int64)
    r2_offs = np.array([offsets[i2] for _, i2 in complete], dtype=np.int64)
    stats = nb.overlap_correct_pairs(
        buf, r1_offs, r2_offs, AGREEMENT_CODES[caller.agreement],
        DISAGREEMENT_CODES[caller.disagreement])
    add_native_overlap_stats(caller.stats, stats)
    out = list(records)
    for i in touched:
        end = offsets[i] + len(records[i].data)
        out[i] = RawRecord(bytes(buf[offsets[i]:end]))
    return out


AGREEMENT_CODES = {"consensus": 0, "max-qual": 1, "pass-through": 2}
DISAGREEMENT_CODES = {"consensus": 0, "mask-both": 1, "mask-lower-qual": 2}
