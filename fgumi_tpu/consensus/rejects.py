"""Rejects-stream plumbing shared by the consensus callers and commands.

The reference treats the rejects BAM as a first-class secondary output of
the pipeline (base.rs:1838, used by simplex/duplex/codec/filter/correct);
here the callers accumulate rejected RawRecords via RejectTracking and the
commands drain them through a RejectsSink.
"""


class RejectTracking:
    """Mixin: rejected-raw-record accumulation (no-op unless enabled)."""

    def _init_rejects(self, track_rejects: bool):
        self.track_rejects = track_rejects
        self.rejected_reads = []

    def _reject_records(self, records):
        if self.track_rejects:
            self.rejected_reads.extend(records)

    def take_rejects(self):
        out = self.rejected_reads
        self.rejected_reads = []
        return out


class RejectsSink:
    """Optional rejects BAM writer: no-ops when no path was requested.

    Rejects keep the INPUT header (raw RG/PG/contig metadata preserved),
    matching the reference's secondary-output convention.
    """

    def __init__(self, path, header):
        self._writer = None
        if path is not None:
            from ..io.bam import BamWriter

            self._writer = BamWriter(path, header)

    def drain(self, caller):
        if self._writer is not None:
            for rec in caller.take_rejects():
                self._writer.write_record(rec)

    def close(self):
        if self._writer is not None:
            self._writer.close()

    def discard(self):
        """Error path: drop the temp file instead of committing a partial
        rejects BAM under the final name (same contract as BamWriter)."""
        if self._writer is not None:
            self._writer.discard()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.close()
        else:
            self.discard()
